"""End-to-end CV trainer tests on synthetic data (fast, tiny model)."""

import numpy as np

from commefficient_tpu.train import cv_train


class TestCvTrainSmoke:
    def test_smoke_sketch_mode(self):
        """--test smoke: tiny model, tiny sketch, 1 round per epoch
        (the reference's de-facto integration test, SURVEY.md §4)."""
        results = cv_train.main([
            "--test", "--dataset_name", "Synthetic",
            "--mode", "sketch", "--error_type", "virtual",
            "--local_momentum", "0",
            "--num_clients", "10", "--num_workers", "2",
            "--local_batch_size", "4", "--num_epochs", "2",
            "--lr_scale", "0.1", "--pivot_epoch", "1",
        ])
        assert len(results) == 2
        assert np.isfinite(results[-1]["train_loss"])
        assert np.isfinite(results[-1]["test_acc"])
        assert results[-1]["up (MiB)"] > 0

    def test_smoke_fedavg(self):
        results = cv_train.main([
            "--test", "--dataset_name", "Synthetic",
            "--mode", "fedavg", "--local_momentum", "0",
            "--local_batch_size", "-1", "--fedavg_batch_size", "4",
            "--num_clients", "10", "--num_workers", "2",
            "--num_epochs", "1", "--lr_scale", "0.1",
            "--pivot_epoch", "0.5",
        ])
        assert len(results) == 1
        assert np.isfinite(results[-1]["train_loss"])

    def test_learns_uncompressed(self):
        """A real (non---test) run on an easy synthetic task must beat
        chance accuracy within a few epochs."""
        results = cv_train.main([
            "--dataset_name", "Synthetic",
            "--mode", "uncompressed", "--error_type", "none",
            "--local_momentum", "0", "--virtual_momentum", "0.9",
            "--num_clients", "10", "--num_workers", "2",
            "--local_batch_size", "8", "--num_epochs", "3",
            "--lr_scale", "1.0", "--pivot_epoch", "1",
            "--model", "ResNet9", "--test",
        ])
        # --test shrinks the model; blobs are separable, so even the
        # 1-channel net should move off chance by the last epoch
        assert results[-1]["train_loss"] < results[0]["train_loss"] + 0.5


class TestFixupLrGroups:
    def test_param_group_indices_partition(self):
        """bias/scale/other index groups partition the flat vector
        exactly (every coordinate in exactly one group)."""
        import jax
        import jax.numpy as jnp

        from commefficient_tpu.models import get_model
        from commefficient_tpu.ops.vec import (flatten_params,
                                               param_group_indices)

        cls = get_model("FixupResNet9")
        m = cls(**cls.test_config())
        p = m.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, 32, 32, 3)))["params"]
        flat, _ = flatten_params(p)
        bias, scale, other = param_group_indices(
            p, cv_train.fixup_bias_name, cv_train.fixup_scale_name)
        all_idx = np.concatenate([bias, scale, other])
        assert len(all_idx) == flat.size
        assert len(np.unique(all_idx)) == flat.size
        assert len(bias) > 0 and len(scale) > 0 and len(other) > 0

    def test_resnet18_scalars_in_01x_groups(self):
        """FixupResNet18 names its fixup scalars add1a/add1b/add2a/
        add2b/mul — every one of them (and nothing kernel-shaped) must
        land in a 0.1x group, matching the reference's substring match
        on 'add1a.bias'/'mul.scale' torch names (fixup_resnet18.py)."""
        import jax
        import jax.numpy as jnp
        from jax.tree_util import keystr, tree_flatten_with_path

        from commefficient_tpu.models import get_model
        from commefficient_tpu.ops.vec import (flatten_params,
                                               param_group_indices)

        cls = get_model("FixupResNet18")
        m = cls()
        p = m.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, 32, 32, 3)))["params"]
        flat, _ = flatten_params(p)
        bias, scale, other = param_group_indices(
            p, cv_train.fixup_bias_name, cv_train.fixup_scale_name)
        # partition
        all_idx = np.concatenate([bias, scale, other])
        assert len(all_idx) == flat.size
        assert len(np.unique(all_idx)) == flat.size
        # every scalar leaf (the fixup params are all scalars) is in a
        # 0.1x group; every kernel is in the 1.0x group
        leaves, _ = tree_flatten_with_path(p)
        offset = 0
        tenth = set(bias.tolist()) | set(scale.tolist())
        n_scalars = 0
        for path, leaf in leaves:
            n = int(np.prod(leaf.shape)) if leaf.shape else 1
            span = set(range(offset, offset + n))
            if leaf.size == 1 and "kernel" not in keystr(path):
                n_scalars += 1
                assert span <= tenth, f"scalar {keystr(path)} not 0.1x"
            elif "kernel" in keystr(path):
                assert span.isdisjoint(tenth), \
                    f"kernel {keystr(path)} wrongly 0.1x"
            offset += n
        assert n_scalars > 0

    def test_resnet50_bottleneck_scalars_in_01x_groups(self):
        """FixupBottleneck declares bias3a/bias3b — every scalar leaf
        of a (tiny) FixupResNet50 must land in a 0.1x group and every
        kernel in the 1.0x group (the regex anchoring must not drop
        the third-conv biases)."""
        import jax
        import jax.numpy as jnp
        from jax.tree_util import keystr, tree_flatten_with_path

        from commefficient_tpu.models import get_model
        from commefficient_tpu.ops.vec import (flatten_params,
                                               param_group_indices)

        m = get_model("FixupResNet50")(num_classes=5,
                                       stage_sizes=(1, 1, 1, 1))
        p = m.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, 64, 64, 3)))["params"]
        flat, _ = flatten_params(p)
        bias, scale, other = param_group_indices(
            p, cv_train.fixup_bias_name, cv_train.fixup_scale_name)
        leaves, _ = tree_flatten_with_path(p)
        tenth = set(bias.tolist()) | set(scale.tolist())
        offset = 0
        saw_bias3 = False
        for path, leaf in leaves:
            n = int(np.prod(leaf.shape)) if leaf.shape else 1
            span = set(range(offset, offset + n))
            name = keystr(path)
            if leaf.size == 1 and "kernel" not in name:
                assert span <= tenth, f"scalar {name} not 0.1x"
                saw_bias3 = saw_bias3 or "bias3" in name
            elif "kernel" in name:
                assert span.isdisjoint(tenth), f"kernel {name} 0.1x"
            offset += n
        assert saw_bias3, "fixture lost its bias3 scalars"

    def test_name_match_anchored_to_leaf_segment(self):
        """The 0.1x groups match the EXACT final path segment, not a
        bare substring — a hypothetical parameter whose path merely
        contains 'bias'/'add'/'scale' must stay in the 1.0x group
        (round-2 advisor finding)."""
        for name in ("['FixupBlock_0']['add1a']", "['bias1a']",
                     "['Dense_0']['bias']", "['bias2']",
                     "['FixupBottleneck_0']['bias3a']",
                     "['FixupBottleneck_0']['bias3b']"):
            assert cv_train.fixup_bias_name(name), name
        for name in ("['mul']", "['Block_0']['scale']",):
            assert cv_train.fixup_scale_name(name), name
        for name in ("['additive_embed']", "['addnorm']['kernel']",
                     "['bias_corrector']", "['add1a']['kernel']"):
            assert not cv_train.fixup_bias_name(name), name
        for name in ("['rescale_factor']", "['scale_mlp']['kernel']",
                     "['multiplier']", "['mul']['kernel']"):
            assert not cv_train.fixup_scale_name(name), name

    def test_lr_vector_alignment(self):
        """FedOptimizer.get_lr with index groups: each coordinate gets
        its own group's LR (reference cv_train.py:366-376 semantics,
        but aligned with the flat vector)."""
        import jax
        import jax.numpy as jnp

        from commefficient_tpu.config import Config
        from commefficient_tpu.models import get_model
        from commefficient_tpu.ops.vec import param_group_indices
        from commefficient_tpu.runtime import FedModel, FedOptimizer

        cls = get_model("FixupResNet9")
        m = cls(**cls.test_config())
        p = m.init(jax.random.PRNGKey(0),
                   jnp.zeros((1, 32, 32, 3)))["params"]
        args = Config(mode="uncompressed", error_type="none",
                      local_momentum=0.0, num_workers=2,
                      local_batch_size=2, num_clients=4,
                      dataset_name="CIFAR10", seed=0)

        def loss(params, batch, cfg):
            return jnp.float32(0.0), ()

        model = FedModel(m, p, loss, args)
        bias, scale, other = param_group_indices(
            p, cv_train.fixup_bias_name, cv_train.fixup_scale_name)
        opt = FedOptimizer([{"lr": 0.1, "index": bias},
                            {"lr": 0.1, "index": scale},
                            {"lr": 1.0, "index": other}], args)
        lr = np.asarray(opt.get_lr())
        assert lr.shape == (args.grad_size,)
        assert np.all(lr[bias] == np.float32(0.1))
        assert np.all(lr[scale] == np.float32(0.1))
        assert np.all(lr[other] == np.float32(1.0))

    def test_fixup_end_to_end(self):
        """Training with the Fixup LR groups runs and stays finite
        (the vector-LR server step compiles in every mode)."""
        results = cv_train.main([
            "--test", "--dataset_name", "Synthetic",
            "--mode", "uncompressed", "--error_type", "none",
            "--local_momentum", "0", "--virtual_momentum", "0.9",
            "--num_clients", "10", "--num_workers", "2",
            "--local_batch_size", "4", "--num_epochs", "1",
            "--lr_scale", "0.1", "--pivot_epoch", "0.5",
            "--model", "FixupResNet9",
        ])
        assert np.isfinite(results[-1]["train_loss"])


class TestBatchNormRunningStats:
    """--batchnorm parity mode: the server blends participating
    clients' batch statistics into one running-stats state and eval
    normalizes with it — so eval metrics are invariant to the eval
    batch composition (reference models/resnet9.py BN eval via
    nn.BatchNorm2d running stats)."""

    def _setup(self):
        import jax
        import jax.numpy as jnp

        from commefficient_tpu.config import Config
        from commefficient_tpu.models import get_model
        from commefficient_tpu.runtime import FedModel, FedOptimizer
        from commefficient_tpu.train.cv_train import (
            make_bn_stats_fn, make_compute_loss,
            make_compute_loss_eval)

        cls = get_model("ResNet9")
        module = cls(do_batchnorm=True, **cls.test_config())
        variables = module.init(jax.random.PRNGKey(0),
                                jnp.zeros((1, 32, 32, 3)), train=True)
        params, init_stats = variables["params"], \
            variables["batch_stats"]
        assert init_stats  # BN collection exists
        args = Config(mode="uncompressed", error_type="none",
                      local_momentum=0.0, virtual_momentum=0.9,
                      num_workers=2, local_batch_size=4,
                      num_clients=6, dataset_name="CIFAR10", seed=0)
        model = FedModel(
            module, params, make_compute_loss(module, init_stats),
            args, compute_loss_val=make_compute_loss_eval(module),
            stats_fn=make_bn_stats_fn(module, init_stats),
            init_model_state=init_stats)
        opt = FedOptimizer([{"lr": 0.05}], args)
        return model, opt, init_stats

    def _train_round(self, model, opt, seed=0):
        rng = np.random.RandomState(seed)
        batch = {
            "x": rng.randn(2, 4, 32, 32, 3).astype(np.float32),
            "y": rng.randint(0, 10, (2, 4)),
            "mask": np.ones((2, 4), np.float32),
            "client_ids": np.array([0, 1], np.int32),
        }
        model(batch)
        opt.step()
        return batch

    def test_stats_update_and_blend(self):
        import jax

        model, opt, init_stats = self._setup()
        before = jax.tree_util.tree_leaves(init_stats)
        self._train_round(model, opt)
        after = jax.tree_util.tree_leaves(model.model_state)
        # running stats moved off init by the 0.1 blend
        changed = [not np.allclose(np.asarray(a), np.asarray(b))
                   for a, b in zip(before, after)]
        assert any(changed)
        # vars stay positive (0.9*1 + 0.1*batch_var)
        for path_leaf in jax.tree_util.tree_leaves(model.model_state):
            assert np.all(np.isfinite(np.asarray(path_leaf)))

    def test_eval_invariant_to_batch_composition(self):
        model, opt, _ = self._setup()
        self._train_round(model, opt)
        model.train(False)

        rng = np.random.RandomState(1)
        S, B = 2, 4
        x = rng.randn(S * B, 32, 32, 3).astype(np.float32)
        y = rng.randint(0, 10, S * B)

        def run_val(order, s, b):
            xo, yo = x[order], y[order]
            batch = {
                "x": xo.reshape(s, b, 32, 32, 3),
                "y": yo.reshape(s, b),
                "mask": np.ones((s, b), np.float32),
            }
            loss_s, acc_s, counts = model(batch)
            # weighted mean over shards = sample mean (mask all-real)
            w = counts / counts.sum()
            return (np.sum(loss_s * w), np.sum(acc_s * w))

        base = run_val(np.arange(S * B), S, B)
        perm = rng.permutation(S * B)
        shuffled = run_val(perm, S, B)
        resized = run_val(np.arange(S * B), 4, 2)  # different shards
        np.testing.assert_allclose(base, shuffled, rtol=1e-5)
        np.testing.assert_allclose(base, resized, rtol=1e-5)

    def test_masked_stats_ignore_padded_rows(self):
        """Recorded batch statistics over a padded batch equal the
        statistics of the unpadded batch: padded zero rows must not
        dilute the mean or skew the variance."""
        import jax
        import jax.numpy as jnp

        from commefficient_tpu.models.norms import BatchStatNorm

        norm = BatchStatNorm(track_stats=True)
        rng = np.random.RandomState(0)
        real = rng.randn(3, 4, 4, 2).astype(np.float32) + 1.5
        padded = np.concatenate(
            [real, np.zeros((5, 4, 4, 2), np.float32)])
        mask = np.array([1, 1, 1, 0, 0, 0, 0, 0], np.float32)

        v = norm.init(jax.random.PRNGKey(0), jnp.asarray(real))
        _, upd_real = norm.apply(v, jnp.asarray(real),
                                 jnp.ones(3, jnp.float32),
                                 mutable=["batch_stats"])
        _, upd_pad = norm.apply(v, jnp.asarray(padded),
                                jnp.asarray(mask),
                                mutable=["batch_stats"])
        for k in ("mean", "var"):
            np.testing.assert_allclose(
                np.asarray(upd_pad["batch_stats"][k]),
                np.asarray(upd_real["batch_stats"][k]), rtol=1e-5)

    def test_recorded_var_unbiased_torch_parity(self):
        """The RECORDED batch variance carries the Bessel n/(n-1)
        correction: torch nn.BatchNorm2d normalizes with the biased
        estimate but updates running_var with the unbiased one, and the
        server blend claims parity with torch BN eval (round-2 advisor
        finding). momentum=1.0 makes torch's running_var equal the
        batch's unbiased var directly."""
        import jax
        import jax.numpy as jnp
        import torch

        from commefficient_tpu.models.norms import BatchStatNorm

        rng = np.random.RandomState(3)
        x = rng.randn(4, 6, 6, 5).astype(np.float32) * 2.0 + 0.7

        norm = BatchStatNorm(track_stats=True)
        v = norm.init(jax.random.PRNGKey(0), jnp.asarray(x))
        _, upd = norm.apply(v, jnp.asarray(x),
                            mutable=["batch_stats"])

        tbn = torch.nn.BatchNorm2d(5, momentum=1.0)
        tbn.train()
        with torch.no_grad():
            tbn(torch.from_numpy(x.transpose(0, 3, 1, 2)))
        np.testing.assert_allclose(
            np.asarray(upd["batch_stats"]["var"]),
            tbn.running_var.numpy(), rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(upd["batch_stats"]["mean"]),
            tbn.running_mean.numpy(), rtol=1e-4, atol=1e-5)
        # masked path agrees with the unmasked one on an all-real batch
        _, upd_m = norm.apply(v, jnp.asarray(x),
                              jnp.ones(4, jnp.float32),
                              mutable=["batch_stats"])
        np.testing.assert_allclose(
            np.asarray(upd_m["batch_stats"]["var"]),
            tbn.running_var.numpy(), rtol=1e-4)

    def test_resume_from_pre_batchnorm_checkpoint(self, tmp_path):
        """A checkpoint written without BN running stats (pre-
        batchnorm format) still restores weights/optimizer state; the
        stats fall back to fresh init with a warning instead of a
        hard failure (round-2 advisor finding)."""
        import json
        import warnings

        import jax

        from commefficient_tpu.runtime.checkpoint import (
            load_checkpoint, save_checkpoint)

        model, opt, init_stats = self._setup()
        self._train_round(model, opt)
        path = str(tmp_path / "c.npz")
        save_checkpoint(path, model, opt)
        # strip the bnstats entries, simulating the older format
        with np.load(path, allow_pickle=False) as z:
            kept = {k: z[k] for k in z.files
                    if not k.startswith("bnstats:")}
        stripped = str(tmp_path / "old.npz")
        np.savez(stripped, **kept)

        model2, opt2, _ = self._setup()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            load_checkpoint(stripped, model2, opt2)
        assert any("running stats" in str(x.message) for x in w)
        np.testing.assert_array_equal(
            np.asarray(model2.ps_weights),
            np.asarray(model.ps_weights))
        for a, b in zip(jax.tree_util.tree_leaves(model2.model_state),
                        jax.tree_util.tree_leaves(init_stats)):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b))

    def test_checkpoint_roundtrip_carries_stats(self, tmp_path):
        import jax

        from commefficient_tpu.runtime.checkpoint import (
            load_checkpoint, save_checkpoint)

        model, opt, _ = self._setup()
        self._train_round(model, opt)
        want = [np.asarray(leaf) for leaf in
                jax.tree_util.tree_leaves(model.model_state)]
        path = str(tmp_path / "c.npz")
        save_checkpoint(path, model, opt)

        model2, opt2, _ = self._setup()
        load_checkpoint(path, model2, opt2)
        got = [np.asarray(leaf) for leaf in
               jax.tree_util.tree_leaves(model2.model_state)]
        for a, b in zip(want, got):
            np.testing.assert_array_equal(a, b)


class TestFinetune:
    def test_merge_replaces_only_mismatched_head(self):
        import jax
        import jax.numpy as jnp
        from commefficient_tpu.models import get_model
        from commefficient_tpu.train.cv_train import merge_finetune_params

        mk = lambda n: get_model("ResNet9")(
            num_classes=n,
            channels={"prep": 2, "layer1": 2, "layer2": 2, "layer3": 2})
        p10 = mk(10).init(jax.random.PRNGKey(0),
                          jnp.zeros((1, 32, 32, 3)))["params"]
        p4 = mk(4).init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 32, 32, 3)))["params"]
        merged, replaced = merge_finetune_params(p4, p10)
        assert replaced == ["Dense_0/kernel"]
        # body copied from source, head kept fresh
        import numpy as np
        np.testing.assert_array_equal(
            np.asarray(merged["ConvBN_0"]["Conv_0"]["kernel"]),
            np.asarray(p10["ConvBN_0"]["Conv_0"]["kernel"]))
        np.testing.assert_array_equal(
            np.asarray(merged["Dense_0"]["kernel"]),
            np.asarray(p4["Dense_0"]["kernel"]))

    def test_finetune_end_to_end(self, tmp_path):
        """Train + checkpoint, then a --finetune run loads the body."""
        from commefficient_tpu.train import cv_train

        base = [
            "--test", "--dataset_name", "Synthetic",
            "--mode", "uncompressed", "--error_type", "none",
            "--local_momentum", "0", "--virtual_momentum", "0",
            "--num_clients", "10", "--num_workers", "2",
            "--local_batch_size", "4", "--num_epochs", "1",
            "--lr_scale", "0.1", "--pivot_epoch", "1",
        ]
        cv_train.main(base + ["--checkpoint",
                              "--checkpoint_path", str(tmp_path)])
        out = cv_train.main(base + ["--finetune",
                                    "--finetune_path", str(tmp_path)])
        assert len(out) == 1


class TestMixup:
    def test_apply_mixup_mixes_within_client_only(self):
        import numpy as np
        from commefficient_tpu.train.cv_train import apply_mixup

        rng = np.random.RandomState(0)
        W, B = 2, 4
        x = np.arange(W * B, dtype=np.float32).reshape(W, B, 1, 1, 1)
        y = np.arange(W * B, dtype=np.int32).reshape(W, B)
        mask = np.ones((W, B), np.float32)
        mask[1, 2:] = 0.0  # client 1 has 2 real rows
        out = apply_mixup({"x": x, "y": y, "mask": mask}, 1.0, rng)
        lam = out["lam"][0, 0]
        assert 0.0 <= lam <= 1.0
        # mixed values stay within each client's own row range
        for w in range(W):
            real = np.nonzero(mask[w] > 0)[0]
            lo, hi = x[w, real].min(), x[w, real].max()
            assert (out["x"][w, real] >= lo - 1e-6).all()
            assert (out["x"][w, real] <= hi + 1e-6).all()
            # y_b is a permutation of the client's own labels
            assert set(out["y_b"][w, real]) <= set(y[w, real])
        # padded rows untouched
        np.testing.assert_array_equal(out["x"][1, 2:], x[1, 2:])

    def test_mixup_end_to_end_smoke(self):
        from commefficient_tpu.train import cv_train

        results = cv_train.main([
            "--test", "--dataset_name", "Synthetic",
            "--mode", "uncompressed", "--error_type", "none",
            "--local_momentum", "0", "--num_clients", "10",
            "--num_workers", "2", "--local_batch_size", "4",
            "--num_epochs", "1", "--lr_scale", "0.1",
            "--pivot_epoch", "1", "--mixup", "--mixup_alpha", "0.5",
        ])
        assert np.isfinite(results[-1]["train_loss"])


class TestModelConfigs:
    def test_fixup50_overlay_respects_explicit_flags(self):
        from commefficient_tpu.config import parse_args
        from commefficient_tpu.models.configs import get_model_config

        defaults = parse_args(0.4, []).__dict__
        mc = get_model_config("FixupResNet50")
        # user left lr_scale at default, set weight_decay explicitly
        args = parse_args(0.4, ["--model", "FixupResNet50",
                                "--weight_decay", "0.123"])
        applied = mc.set_args(args, defaults)
        assert args.lr_scale == 0.1 and "lr_scale" in applied
        assert args.weight_decay == 0.123  # explicit flag wins
        assert "weight_decay" not in applied
        # shape: peak 1.0, 10x decays at 30/60/90; effective LR is
        # args.lr_scale * shape(epoch)
        assert abs(mc.lr_schedule_shape(0) - 1.0) < 1e-9
        assert abs(mc.lr_schedule_shape(45) - 0.1) < 1e-9
        assert abs(mc.lr_schedule_shape(95) - 0.001) < 1e-9

    def test_unknown_model_has_no_config(self):
        from commefficient_tpu.models.configs import get_model_config
        assert get_model_config("ResNet9") is None


class TestPipelinedRounds:
    def test_pipeline_depth_identical_results(self):
        """--pipeline_depth only changes WHEN results cross to the
        host: every epoch metric, including the byte-accounting
        totals, must match the synchronous run exactly."""
        base = [
            "--test", "--dataset_name", "Synthetic",
            "--mode", "sketch", "--error_type", "virtual",
            "--local_momentum", "0", "--virtual_momentum", "0.9",
            "--num_clients", "10", "--num_workers", "2",
            "--local_batch_size", "4", "--num_epochs", "2",
            "--lr_scale", "0.1", "--pivot_epoch", "1", "--seed", "5",
        ]
        sync = cv_train.main(base)
        piped = cv_train.main(base + ["--pipeline_depth", "4"])
        assert len(sync) == len(piped) == 2
        for rs, rp in zip(sync, piped):
            for key in ("train_loss", "train_acc", "test_acc",
                        "down (MiB)", "up (MiB)"):
                assert rs[key] == rp[key], key


class TestDeterminism:
    def test_same_seed_identical_training(self):
        """Two identical runs (same seed) must produce bit-identical
        epoch metrics end to end (engine, data order, init)."""
        base = [
            "--test", "--dataset_name", "Synthetic",
            "--mode", "sketch", "--error_type", "virtual",
            "--local_momentum", "0", "--virtual_momentum", "0.9",
            "--num_clients", "10", "--num_workers", "2",
            "--local_batch_size", "4", "--num_epochs", "2",
            "--lr_scale", "0.1", "--pivot_epoch", "1", "--seed", "33",
        ]
        a = cv_train.main(base)
        b = cv_train.main(base)
        assert len(a) == len(b) == 2
        for ra, rb in zip(a, b):
            assert ra["train_loss"] == rb["train_loss"]
            assert ra["test_acc"] == rb["test_acc"]
