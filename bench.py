"""Headline benchmark: federated client-updates/sec, ResNet9/CIFAR10
config at a lane-aligned twin of the reference's sketch geometry (see
below — part of the speedup vs the XLA path is that geometry choice).

Runs the full FetchSGD round on whatever accelerator JAX provides (the
driver runs this on real TPU): ResNet9 (~6.6M params), 8 clients/round
x local batch 8, count-sketch 5 rows x 524288 cols (2^19 — the
lane-aligned twin of the reference's 500000 default, within 5% of the
same compression ratio; alignment engages the fused Pallas kernels,
3.5x faster than the XLA path on v5e) + unsketch k=50k + server step.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is the ratio to BASELINE_CLIENTS_PER_SEC, an estimate
of the reference PyTorch implementation's single-A100 throughput on
the same config (the repo publishes no numbers — BASELINE.md; estimate
derived from per-round fwd/bwd + CSVec cost at batch 8).
"""

import argparse
import json

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import Config
from commefficient_tpu.core.rounds import (ClientStates,
                                           build_client_round,
                                           build_server_round,
                                           round_plan)
from commefficient_tpu.core.server import ServerState
from commefficient_tpu.models import get_model
from commefficient_tpu.ops.vec import flatten_params
from commefficient_tpu.telemetry import clock
from commefficient_tpu.train.cv_train import make_compute_loss

BASELINE_CLIENTS_PER_SEC = 60.0  # est. reference single-A100 (see doc)

W, B, NUM_CLIENTS, ROUNDS = 8, 8, 100, 100


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ledger", type=str, default="",
                    help="append the result as a telemetry JSONL bench "
                         "record (the stdout line is unchanged)")
    bench_args = ap.parse_args(argv)
    cfg = Config(mode="sketch", error_type="virtual", local_momentum=0.0,
                 virtual_momentum=0.9, weight_decay=5e-4,
                 num_workers=W, local_batch_size=B,
                 k=50000, num_rows=5, num_cols=524288, num_blocks=20,
                 dataset_name="CIFAR10", seed=21,
                 # EXACT selection: since round 3 the threshold-select
                 # path (nibble search + fused Pallas take-mask,
                 # ops/topk.py) makes exact recovery FASTER than
                 # approx_max_k at this scale (6.5 vs 9.4 ms/round) —
                 # the headline runs the reference-parity default
                 approx_topk=False)

    module = get_model("ResNet9")(num_classes=10, dtype=jnp.bfloat16)
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 32, 32, 3)))["params"]
    flat, unravel = flatten_params(params)
    cfg.grad_size = int(flat.size)

    compute_loss = make_compute_loss(module)

    def loss_tree(p, batch):
        return compute_loss(p, batch, cfg)

    client_round = jax.jit(build_client_round(
        cfg, None, B, tree_loss=loss_tree, unravel=unravel))
    server_round = jax.jit(build_server_round(cfg))

    rng = np.random.RandomState(0)
    batch = {
        "x": jnp.asarray(rng.randn(W, B, 32, 32, 3).astype(np.float32)),
        "y": jnp.asarray(rng.randint(0, 10, (W, B)).astype(np.int32)),
        "mask": jnp.ones((W, B), jnp.float32),
    }
    ids = jnp.arange(W, dtype=jnp.int32)
    ps = flat
    cs = ClientStates.init(cfg, NUM_CLIENTS, ps)
    ss = ServerState.init(cfg)
    key = jax.random.PRNGKey(0)

    @jax.jit
    def run_rounds(ps, ss):
        """ROUNDS federated rounds chained in one program — measures
        true device throughput (per-dispatch tunnel latency to the
        remote chip is ~70 ms and would otherwise dominate; a real
        deployment batches rounds the same way). Returns a device-
        computed scalar checksum so forcing completion ships 4 bytes,
        not the 26 MB weight vector, through the relay."""
        def body(r, carry):
            ps, ss = carry
            res = client_round(ps, cs, batch, ids,
                               jax.random.fold_in(key, r), 1.0)
            ps, ss, _, _, _ = server_round(ps, ss, res.aggregated,
                                        jnp.float32(0.1))
            return ps, ss
        ps, ss = jax.lax.fori_loop(0, ROUNDS, body, (ps, ss))
        return ps, ss, jnp.sum(ps)

    # warmup/compile
    w_ps, w_ss, w_sum = run_rounds(ps, ss)
    assert np.isfinite(float(w_sum))

    # median of 3 timed repetitions: dispatch rides a remote relay
    # with ~±15% run-to-run variance, so a single draw is noisy
    times = []
    for _ in range(3):
        t0 = clock.tick()
        _, _, checksum = run_rounds(ps, ss)
        float(checksum)
        times.append(clock.tick() - t0)
    dt = sorted(times)[1]

    clients_per_sec = W * ROUNDS / dt
    line = {
        "metric": "client_updates_per_sec_resnet9_sketch",
        "value": round(clients_per_sec, 2),
        "unit": "clients/s",
        "vs_baseline": round(clients_per_sec / BASELINE_CLIENTS_PER_SEC,
                             3),
    }
    # the stdout line is the harness contract — it stays exactly as-is;
    # --ledger additionally appends schema-v1 records for
    # scripts/telemetry_report.py
    print(json.dumps(line))
    if bench_args.ledger:
        from commefficient_tpu.telemetry import (JSONLSink,
                                                 make_bench_record,
                                                 make_meta_record)
        sink = JSONLSink(bench_args.ledger)
        sink.write(make_meta_record(
            bench="bench.py", rounds=ROUNDS, workers=W,
            local_batch_size=B, plan=round_plan(cfg)))
        sink.write(make_bench_record(
            line["metric"], line["value"], line["unit"],
            vs_baseline=line["vs_baseline"],
            round_times_s=[round(t, 4) for t in times],
            backend=jax.default_backend()))
        sink.close()
        # run manifest: makes this bench discoverable by
        # scripts/perf_gate.py --runs_dir / telemetry_report --runs_dir
        from commefficient_tpu.telemetry import registry
        registry.maybe_write_manifest(
            bench_args, bench={line["metric"]: line},
            extra={"bench_config": registry.config_dict(cfg),
                   "rounds": ROUNDS, "workers": W})


if __name__ == "__main__":
    main()
