"""Two-process client-store smoke: shard-per-process ownership.

Each worker joins the JAX multi-controller runtime (2 localhost CPU
processes x 2 devices), builds a ``--clientstore host`` FedModel over
a tiny linear task, and drives deterministic rounds whose participants
span both processes' shards. Asserted in-worker:

- ``shard_range`` gives each process its contiguous client-id block;
- the store only ever persists rows it owns (``written_ids`` stays
  inside the shard), while the cross-process allgather-sum rebuilds
  every participant row identically on both processes;
- the host placement's weight trajectory is bit-identical to a
  device-placement run on the same spanning mesh;
- a checkpoint written through the store (process 0's shard in the
  main archive, process 1's in a ``.shard1.npz`` side file) resumes
  bit-exactly.

The launcher parses per-worker result lines and prints
``CLIENTSTORE_MULTIHOST_OK`` only if both workers exit 0 and agree.

Usage:  python scripts/clientstore_multihost.py
"""

import argparse
import os
import socket
import subprocess
import sys
import tempfile

DEVICES_PER_PROC = 2
NC = 10   # population; shard per process: [0,5) / [5,10)
W = 4     # participants per round == total devices
B = 2
D = 5


def worker(args):
    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from commefficient_tpu.clientstore import shard_range
    from commefficient_tpu.config import Config
    from commefficient_tpu.parallel.mesh import initialize_multihost
    from commefficient_tpu.runtime.checkpoint import (load_checkpoint,
                                                      save_checkpoint)
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)

    initialize_multihost(args.coordinator, args.num_processes,
                         args.process_id)
    assert jax.process_index() == args.process_id
    assert jax.device_count() == DEVICES_PER_PROC * args.num_processes

    lo, hi = shard_range(NC)
    assert (lo, hi) == ((0, 5) if args.process_id == 0 else (5, 10)), \
        (lo, hi)

    def loss(params, batch, cfg):
        pred = batch["x"] @ params["w"]
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
        return l, (l * 0.0 + 1.0,)

    def cfg(placement):
        return Config(mode="local_topk", error_type="local",
                      local_momentum=0.9, virtual_momentum=0.0,
                      k=3, num_workers=W, local_batch_size=B,
                      num_clients=NC, seed=9, clientstore=placement,
                      clientstore_bytes=1 << 16)

    def build(placement):
        model = FedModel(None, {"w": jnp.zeros((D,), jnp.float32)},
                         loss, cfg(placement), padded_batch_size=B)
        return model, FedOptimizer([{"lr": 0.25}], model.args,
                                   model=model)

    # deterministic rounds, same on both processes; participants drawn
    # from the full population so every round crosses both shards
    rng = np.random.RandomState(3)
    rounds = []
    for _ in range(4):
        ids = rng.choice(NC, W, replace=False).astype(np.int32)
        rounds.append((ids, rng.randn(W, B, D).astype(np.float32),
                       rng.randn(W, B).astype(np.float32)))

    def drive(model, opt, rnds):
        traj = []
        for ids, x, y in rnds:
            batch = {"client_ids": ids, "x": jnp.asarray(x),
                     "y": jnp.asarray(y),
                     "mask": jnp.ones((W, B), jnp.float32)}
            model(batch)
            opt.step()
            traj.append(np.asarray(model.ps_weights, np.float64))
        return traj

    # (1) host placement across the 2-process mesh
    mh, oh = build("host")
    assert mh.client_store.owned == (lo, hi)
    assert mh._prefetcher is None  # collectives stay on main thread
    traj_h = drive(mh, oh, rounds)

    written = mh.client_store.written_ids()
    participants = {int(c) for ids, _, _ in rounds for c in ids}
    owned_participants = {c for c in participants if lo <= c < hi}
    assert set(written) == owned_participants, \
        (sorted(written), sorted(owned_participants))

    # the allgather-sum exchange rebuilds the same full rows everywhere
    rows = mh._gather_rows(np.arange(NC, dtype=np.int64))
    row_sum = float(sum(np.abs(v).sum() for v in rows.values()))
    assert row_sum > 0

    # (2) device placement on the same spanning mesh: bit-identical
    md, od = build("device")
    traj_d = drive(md, od, rounds)
    for r, (a, b) in enumerate(zip(traj_h, traj_d)):
        np.testing.assert_array_equal(a, b, err_msg=f"round {r}")

    # (3) checkpoint through the store: main archive + side shard file
    shared = os.environ["CS_SHARED_DIR"]
    path = os.path.join(shared, "ck.npz")
    m1, o1 = build("host")
    drive(m1, o1, rounds[:3])
    save_checkpoint(path, m1, o1, epoch=1)
    m1.finalize()
    assert os.path.exists(path)
    assert os.path.exists(path + ".shard1.npz")
    m2, o2 = build("host")
    load_checkpoint(path, m2, o2)
    traj_r = drive(m2, o2, rounds[3:])
    np.testing.assert_array_equal(traj_h[-1], traj_r[-1])
    m2.finalize()
    mh.finalize()

    print(f"WORKER{args.process_id}_CS "
          f"{traj_h[-1].sum():.12f}/{row_sum:.12f}", flush=True)


def launcher():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    shared_dir = tempfile.mkdtemp(prefix="clientstore_mh_")
    procs, logs = [], []
    for i in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count="
                      f"{DEVICES_PER_PROC}",
            PYTHONPATH=repo_root + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
            CS_SHARED_DIR=shared_dir,
        )
        log = tempfile.TemporaryFile(mode="w+")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--process_id", str(i), "--num_processes", "2",
             "--coordinator", f"localhost:{port}"],
            env=env, stdout=log, stderr=subprocess.STDOUT))
    import time
    deadline = time.time() + 900
    pending = set(range(2))
    failed = False
    while pending and time.time() < deadline:
        for i in list(pending):
            rc = procs[i].poll()
            if rc is not None:
                pending.discard(i)
                failed = failed or rc != 0
        if failed:
            break
        time.sleep(0.5)
    for i in pending:
        procs[i].kill()
    outs = []
    for p, log in zip(procs, logs):
        p.wait(timeout=60)
        log.seek(0)
        outs.append(log.read())
        log.close()
    import shutil
    shutil.rmtree(shared_dir, ignore_errors=True)
    codes = [p.returncode for p in procs]
    if any("Multiprocess computations aren't implemented" in out
           for out in outs):
        # this jaxlib's CPU backend cannot run cross-process
        # computations at all (same limitation hits
        # scripts/multihost_smoke.py) — report an explicit SKIP so the
        # test tier can distinguish "environment can't" from "broken"
        print("CLIENTSTORE_MULTIHOST_SKIP "
              "(CPU backend lacks multiprocess computations)")
        sys.exit(3)
    vals = []
    for i, out in enumerate(outs):
        for line in out.splitlines():
            if line.startswith(f"WORKER{i}_CS "):
                vals.append(line.split()[1])
    if codes != [0, 0] or len(vals) != 2:
        for i, out in enumerate(outs):
            sys.stderr.write(f"--- worker {i} (exit {codes[i]}) ---\n")
            sys.stderr.write(out[-4000:] + "\n")
        sys.exit(1)
    assert vals[0] == vals[1], f"processes disagree: {vals}"
    print(f"CLIENTSTORE_MULTIHOST_OK {vals[0]}")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--process_id", type=int, default=None)
    ap.add_argument("--num_processes", type=int, default=2)
    ap.add_argument("--coordinator", type=str, default=None)
    args = ap.parse_args()
    if args.process_id is None:
        launcher()
    else:
        worker(args)
