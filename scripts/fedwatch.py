#!/usr/bin/env python
"""fedwatch: live-tail console for a running fedservice daemon.

Polls a live-plane exporter (``--live_port``'s ``/metrics``) and
renders one refreshing per-job table — rounds done, round-latency
p95, wire bytes, backlog, staleness, ε spend, SLO burn rate, the
dominant critical-path bucket (--causal_trace runs), alarm fires —
so an operator watches the pod instead of tailing J ledger shards.
Falls back to tailing the ledger shards directly (``--ledger``) when
the daemon has no exporter armed.

    python scripts/fedwatch.py --url http://127.0.0.1:9100
    python scripts/fedwatch.py --ledger runs/svc.jsonl --once

Stdlib only, read-only, and deliberately decoupled from the package
internals: the metrics contract is the Prometheus text exposition the
exporter serves, parsed by the same minimal parser the tests use.
"""

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def parse_prometheus(text):
    """Minimal Prometheus text-exposition (0.0.4) parser:
    ``[(name, labels_dict, value)]``. Handles escaped label values;
    ignores comments/blank lines. Enough for the exporter's own
    output — not a general scraper."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            lab_str, _, val = rest.rpartition("}")
            labels = {}
            i, n = 0, len(lab_str)
            while i < n:
                eq = lab_str.index("=", i)
                key = lab_str[i:eq].strip().lstrip(",").strip()
                assert lab_str[eq + 1] == '"', lab_str
                j = eq + 2
                buf = []
                while lab_str[j] != '"':
                    if lab_str[j] == "\\":
                        nxt = lab_str[j + 1]
                        buf.append({"n": "\n"}.get(nxt, nxt))
                        j += 2
                    else:
                        buf.append(lab_str[j])
                        j += 1
                labels[key] = "".join(buf)
                i = j + 1
            out.append((name.strip(), labels, float(val)))
        else:
            name, _, val = line.rpartition(" ")
            out.append((name.strip(), {}, float(val)))
    return out


def scrape(url):
    with urllib.request.urlopen(url.rstrip("/") + "/metrics",
                                timeout=10) as resp:
        return parse_prometheus(resp.read().decode())


def _fmt(v, unit=""):
    if v is None:
        return "-"
    if isinstance(v, str):        # pre-rendered cells (crit column)
        return v
    if unit == "mib":
        return f"{v / 2**20:.2f}M"
    if abs(v) >= 1000 or v == int(v):
        return f"{v:g}"
    return f"{v:.3g}"


def job_table(samples):
    """Fold scraped samples into one row per ``job`` label."""
    jobs = {}

    def slot(labels):
        return jobs.setdefault(labels.get("job", "?"), {})

    for name, labels, val in samples:
        row = slot(labels)
        if name == "commeff_rounds_total":
            row["rounds"] = val
        elif name == "commeff_round_seconds" \
                and labels.get("quantile") == "0.95":
            row["p95_s"] = val
        elif name == "commeff_clients_per_s":
            row["clients_s"] = val
        elif name == "commeff_uplink_bytes_total":
            row["up"] = val
        elif name == "commeff_downlink_bytes_total":
            row["down"] = val
        elif name == "commeff_job_backlog_total":
            row["backlog"] = val
        elif name == "commeff_async_staleness_max":
            row["stale"] = val
        elif name == "commeff_dp_epsilon":
            row["eps"] = val
        elif name == "commeff_slo_burn":
            row["burn"] = max(row.get("burn", 0.0), val)
        elif name == "commeff_critpath_seconds":
            row.setdefault("critpath", {})[
                labels.get("bucket", "?")] = val
        elif name == "commeff_alarms_total":
            row["alarms"] = row.get("alarms", 0.0) + val
    for row in jobs.values():
        cp = row.pop("critpath", None)
        if cp:
            # last traced round's per-bucket critical-path gauges:
            # the buckets sum to the round wall, so the max bucket's
            # share IS the dominant attribution
            total = sum(cp.values())
            b, s = max(cp.items(), key=lambda kv: kv[1])
            if total > 0:
                row["crit"] = f"{b} {100 * s / total:.0f}%"
    return jobs


COLS = (("job", "job", ""), ("rounds", "rounds", ""),
        ("p95_s", "p95 s", ""), ("clients_s", "cl/s", ""),
        ("up", "up", "mib"), ("down", "down", "mib"),
        ("backlog", "backlog", ""), ("stale", "stale", ""),
        ("eps", "eps", ""), ("burn", "burn", ""),
        ("crit", "crit", ""), ("alarms", "alarms", ""))


def render_table(jobs) -> str:
    rows = [[title for _, title, _ in COLS]]
    for job in sorted(jobs, key=lambda j: (j != "service", j)):
        row = jobs[job]
        rows.append([job] + [_fmt(row.get(key), unit)
                             for key, _, unit in COLS[1:]])
    widths = [max(len(r[i]) for r in rows)
              for i in range(len(COLS))]
    lines = ["  ".join(c.rjust(w) for c, w in zip(r, widths))
             for r in rows]
    lines.insert(1, "-" * len(lines[0]))
    return "\n".join(lines)


def _crit_cell(causal, device_time=None):
    """Dominant critical-path bucket cell ("h2d 62%") for a ledger
    record's causal stamp. The only non-stdlib touch in this script
    — degrades to None when the package isn't importable so the
    console stays usable standalone."""
    import os
    try:
        try:
            from commefficient_tpu.telemetry.critpath import (
                critical_path, dominant_bucket)
        except ImportError:
            # run as `python scripts/fedwatch.py` next to the repo:
            # the checkout root isn't on sys.path yet
            sys.path.insert(0, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), ".."))
            from commefficient_tpu.telemetry.critpath import (
                critical_path, dominant_bucket)
        dom = dominant_bucket(critical_path(causal, device_time))
    except Exception:
        return None
    if dom is None:
        return None
    return f"{dom[0]} {100 * dom[1]:.0f}%"


def ledger_table(path):
    """Exporter-less fallback: derive the same table from the ledger
    shards on disk (base + .job<j> shards)."""
    import glob
    import os

    jobs = {}
    paths = [(p, p.split(".job")[-1].split(".")[0]
              if ".job" in os.path.basename(p) else "service")
             for p in [path] + sorted(
                 glob.glob(glob.escape(path) + ".job*.jsonl"))]
    for p, job in paths:
        if not os.path.isfile(p):
            continue
        row = jobs.setdefault(job, {})
        lats = []
        for line in open(p):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("kind") == "summary" and \
                    rec.get("alarm_fired"):
                row["alarms"] = sum(rec["alarm_fired"].values())
            if rec.get("kind") != "round":
                continue
            row["rounds"] = row.get("rounds", 0) + 1
            spans = rec.get("spans") or {}
            if spans:
                lats.append(sum(spans.values()))
            row["up"] = row.get("up", 0.0) + (
                rec.get("uplink_bytes") or 0.0)
            row["down"] = row.get("down", 0.0) + (
                rec.get("downlink_bytes") or 0.0)
            probes = rec.get("probes") or {}
            if probes.get("job_backlog_total") is not None:
                row["backlog"] = probes["job_backlog_total"]
            if probes.get("async_staleness_max") is not None:
                row["stale"] = probes["async_staleness_max"]
            if probes.get("slo_burn_max") is not None:
                row["burn"] = probes["slo_burn_max"]
            if rec.get("dp_epsilon") is not None:
                row["eps"] = rec["dp_epsilon"]
            causal = rec.get("causal")
            if isinstance(causal, dict):
                crit = _crit_cell(causal, rec.get("device_time"))
                if crit:
                    row["crit"] = crit
        if lats:
            lats.sort()
            row["p95_s"] = lats[min(len(lats) - 1,
                                    int(round(0.95 * (len(lats) - 1))))]
    return jobs


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="live-tail console for a fedservice daemon")
    ap.add_argument("--url", default="",
                    help="exporter base URL, e.g. "
                         "http://127.0.0.1:9100")
    ap.add_argument("--ledger", default="",
                    help="fallback: tail the ledger shards at this "
                         "base path instead of scraping")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="seconds between refreshes")
    ap.add_argument("--iterations", type=int, default=0,
                    help="stop after N refreshes (0 = forever)")
    ap.add_argument("--once", action="store_true",
                    help="render one table and exit")
    args = ap.parse_args(argv)
    if not args.url and not args.ledger:
        ap.error("--url or --ledger required")

    n = 0
    while True:
        try:
            jobs = (job_table(scrape(args.url)) if args.url
                    else ledger_table(args.ledger))
            src = args.url or args.ledger
            out = (f"fedwatch {time.strftime('%H:%M:%S')} {src}\n"
                   + render_table(jobs))
        except (urllib.error.URLError, OSError) as e:
            out = f"fedwatch: scrape failed: {e}"
        if args.once or args.iterations:
            print(out)
        else:
            # ANSI home+clear keeps the table in place like top(1)
            sys.stdout.write("\x1b[H\x1b[2J" + out + "\n")
            sys.stdout.flush()
        n += 1
        if args.once or (args.iterations and n >= args.iterations):
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
