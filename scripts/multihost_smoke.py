"""Two-process multi-controller smoke: one federated round through the
full trainer (round-1 review: ``initialize_multihost`` must be
exercised by a real multi-process run, not just exist).

The launcher spawns two worker processes on localhost CPU (the moral
equivalent of the reference's single-host NCCL topology,
fed_aggregator.py:161-165; SURVEY.md §4 "multi-node without a
cluster"). Each worker joins the JAX multi-controller runtime via
``initialize_multihost``, sees a mesh spanning both processes'
devices, and runs a short synthetic `cv_train` — every process
executes the same SPMD program, and process 0's metrics are checked
finite and identical to process 1's.

Usage:
  python scripts/multihost_smoke.py            # launcher
  (workers are spawned internally with --process_id)
"""

import argparse
import os
import socket
import subprocess
import sys
import tempfile

DEVICES_PER_PROC = 2


def worker(args):
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from commefficient_tpu.train import cv_train

    total = DEVICES_PER_PROC * args.num_processes
    shared = os.environ["SMOKE_SHARED_DIR"]

    # (1) sketch mode; the multi-controller runtime is joined through
    # the trainer's own CLI flags (round-2 review weak #5: a pod user
    # must not have to call initialize_multihost by hand)
    results = cv_train.main([
        "--test", "--dataset_name", "Synthetic",
        "--mode", "sketch", "--error_type", "virtual",
        "--local_momentum", "0",
        "--num_clients", "10", "--num_workers", str(total),
        "--local_batch_size", "4", "--num_epochs", "2",
        "--lr_scale", "0.1", "--pivot_epoch", "1",
        "--coordinator_address", args.coordinator,
        "--num_processes", str(args.num_processes),
        "--process_id", str(args.process_id),
    ])
    assert jax.process_index() == args.process_id
    assert jax.device_count() == total, \
        f"{jax.device_count()} != {total}"
    assert jax.local_device_count() == DEVICES_PER_PROC
    assert np.isfinite(results[-1]["train_loss"])
    assert np.isfinite(results[-1]["test_acc"])
    # SPMD determinism: every process computed identical metrics
    print(f"WORKER{args.process_id}_RESULT "
          f"{results[-1]['train_loss']:.9f}", flush=True)

    # (2) local_topk: per-client momentum+error rows SHARDED across
    # the two processes (round-2 review weak #5 — a local-state mode
    # crossing process boundaries)
    lt_flags = [
        "--test", "--dataset_name", "Synthetic",
        "--mode", "local_topk", "--error_type", "local",
        "--local_momentum", "0.9",
        "--num_clients", "10", "--num_workers", str(total),
        "--local_batch_size", "4",
        "--lr_scale", "0.1", "--pivot_epoch", "1",
        "--schedule_epochs", "2",
    ]
    results = cv_train.main(lt_flags + ["--num_epochs", "2"])
    assert np.isfinite(results[-1]["train_loss"])
    print(f"WORKER{args.process_id}_LT "
          f"{results[-1]['train_loss']:.9f}", flush=True)

    # (3) checkpoint round-trip on the multi-process mesh (round-2
    # review weak #4: save must allgather non-addressable client rows,
    # one process writes, resume restores the sharded placement).
    # A: uninterrupted 2 epochs; B: 1 epoch, "killed", resumed to 2 —
    # final metrics must match A's bit-for-bit.
    row_a = cv_train.main(lt_flags + [
        "--num_epochs", "2", "--checkpoint",
        "--checkpoint_path", os.path.join(shared, "ckptA"),
    ])[-1]
    cv_train.main(lt_flags + [
        "--num_epochs", "1", "--checkpoint", "--checkpoint_every", "1",
        "--checkpoint_path", os.path.join(shared, "ckptB"),
    ])
    row_b = cv_train.main(lt_flags + [
        "--num_epochs", "2", "--checkpoint", "--resume",
        "--checkpoint_path", os.path.join(shared, "ckptB"),
    ])[-1]
    for key in ("train_loss", "train_acc", "test_loss", "test_acc"):
        assert repr(row_a[key]) == repr(row_b[key]), \
            (key, row_a[key], row_b[key])
    print(f"WORKER{args.process_id}_RESUME "
          f"{row_b['train_loss']:.9f}", flush=True)

    # (4) the GPT-2 trainer over the same spanning mesh (round-2
    # review weak #5: the smoke only covered cv_train) — sketch round
    # + sharded validation, per-process synthetic archive dirs (the
    # generator is seed-deterministic, so the data is identical and
    # the SPMD metrics must agree across processes)
    from commefficient_tpu.train import gpt2_train
    results = gpt2_train.main([
        "--test", "--dataset_name", "PERSONA",
        "--dataset_dir",
        os.path.join(shared, f"persona{args.process_id}"),
        "--mode", "sketch", "--error_type", "virtual",
        "--local_momentum", "0", "--virtual_momentum", "0.9",
        "--num_workers", str(total), "--local_batch_size", "2",
        "--num_epochs", "1", "--lr_scale", "0.01",
    ])
    assert np.isfinite(results[-1]["train_loss"])
    assert np.isfinite(results[-1]["val_ppl"])
    print(f"WORKER{args.process_id}_GPT2 "
          f"{results[-1]['train_loss']:.9f}", flush=True)

    # (5) sequence parallelism ACROSS the process boundary (round-3
    # review next #8): --seq_devices = the full 4-device mesh, so the
    # seq axis spans both processes and ring attention's ppermute
    # rides the inter-process transport (the pod user's DCN seq
    # sharding; moral equivalent of the reference's cross-rank NCCL
    # topology, fed_aggregator.py:161-165). Identical metrics on both
    # processes prove the spanning SPMD program agrees end to end.
    results = gpt2_train.main([
        "--test", "--dataset_name", "PERSONA",
        "--dataset_dir",
        os.path.join(shared, f"persona{args.process_id}"),
        "--mode", "sketch", "--error_type", "virtual",
        "--local_momentum", "0", "--virtual_momentum", "0.9",
        "--seq_devices", str(total), "--seq_impl", "ring",
        "--num_workers", "2", "--local_batch_size", "2",
        "--num_epochs", "1", "--lr_scale", "0.01",
    ])
    assert np.isfinite(results[-1]["train_loss"])
    assert np.isfinite(results[-1]["val_ppl"])
    print(f"WORKER{args.process_id}_SP "
          f"{results[-1]['train_loss']:.9f}", flush=True)


def launcher():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    procs = []
    logs = []
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    shared_dir = tempfile.mkdtemp(prefix="multihost_smoke_")
    for i in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count="
                      f"{DEVICES_PER_PROC}",
            PYTHONPATH=repo_root + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
            SMOKE_SHARED_DIR=shared_dir,
        )
        # temp files, not PIPEs: an undrained pipe buffer would
        # deadlock a chatty worker against the poll loop below
        log = tempfile.TemporaryFile(mode="w+")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--process_id", str(i), "--num_processes", "2",
             "--coordinator", f"localhost:{port}"],
            env=env, stdout=log, stderr=subprocess.STDOUT))
    # one shared deadline; if any worker dies or stalls, kill the
    # peers too (a dead coordinator would otherwise hang its partner
    # in jax.distributed.initialize, orphaned past the test timeout)
    import time
    deadline = time.time() + 1200
    pending = set(range(2))
    failed = False
    while pending and time.time() < deadline:
        for i in list(pending):
            rc = procs[i].poll()
            if rc is not None:
                pending.discard(i)
                failed = failed or rc != 0
        if failed:
            break
        time.sleep(0.5)
    if pending:
        for i in pending:
            procs[i].kill()
    outs = []
    for p, log in zip(procs, logs):
        p.wait(timeout=60)
        log.seek(0)
        outs.append(log.read())
        log.close()
    import shutil
    shutil.rmtree(shared_dir, ignore_errors=True)
    codes = [p.returncode for p in procs]
    results = {}
    for i, out in enumerate(outs):
        for line in out.splitlines():
            for tag in ("RESULT", "LT", "RESUME", "GPT2", "SP"):
                if line.startswith(f"WORKER{i}_{tag} "):
                    results.setdefault(tag, []).append(line.split()[1])
    complete = all(len(results.get(tag, [])) == 2
                   for tag in ("RESULT", "LT", "RESUME", "GPT2",
                               "SP"))
    if codes != [0, 0] or not complete:
        for i, out in enumerate(outs):
            sys.stderr.write(f"--- worker {i} (exit {codes[i]}) ---\n")
            sys.stderr.write(out[-4000:] + "\n")
        sys.exit(1)
    for tag, vals in results.items():
        assert vals[0] == vals[1], \
            f"processes disagree on {tag}: {vals}"
    print(f"MULTIHOST_OK loss={results['RESULT'][0]} "
          f"local_topk={results['LT'][0]} "
          f"resume={results['RESUME'][0]} "
          f"gpt2={results['GPT2'][0]} "
          f"sp={results['SP'][0]}")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--process_id", type=int, default=None)
    ap.add_argument("--num_processes", type=int, default=2)
    ap.add_argument("--coordinator", type=str, default=None)
    args = ap.parse_args()
    if args.process_id is None:
        launcher()
    else:
        worker(args)
