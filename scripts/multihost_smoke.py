"""Two-process multi-controller smoke: one federated round through the
full trainer (round-1 review: ``initialize_multihost`` must be
exercised by a real multi-process run, not just exist).

The launcher spawns two worker processes on localhost CPU (the moral
equivalent of the reference's single-host NCCL topology,
fed_aggregator.py:161-165; SURVEY.md §4 "multi-node without a
cluster"). Each worker joins the JAX multi-controller runtime via
``initialize_multihost``, sees a mesh spanning both processes'
devices, and runs a short synthetic `cv_train` — every process
executes the same SPMD program, and process 0's metrics are checked
finite and identical to process 1's.

Usage:
  python scripts/multihost_smoke.py            # launcher
  (workers are spawned internally with --process_id)
"""

import argparse
import os
import socket
import subprocess
import sys
import tempfile

DEVICES_PER_PROC = 2


def worker(args):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from commefficient_tpu.parallel.mesh import initialize_multihost

    pid = initialize_multihost(args.coordinator, args.num_processes,
                               args.process_id)
    assert pid == args.process_id
    total = DEVICES_PER_PROC * args.num_processes
    assert jax.device_count() == total, \
        f"{jax.device_count()} != {total}"
    assert jax.local_device_count() == DEVICES_PER_PROC

    from commefficient_tpu.train import cv_train
    results = cv_train.main([
        "--test", "--dataset_name", "Synthetic",
        "--mode", "sketch", "--error_type", "virtual",
        "--local_momentum", "0",
        "--num_clients", "10", "--num_workers", str(total),
        "--local_batch_size", "4", "--num_epochs", "2",
        "--lr_scale", "0.1", "--pivot_epoch", "1",
    ])
    import numpy as np
    assert np.isfinite(results[-1]["train_loss"])
    assert np.isfinite(results[-1]["test_acc"])
    # SPMD determinism: every process computed identical metrics
    print(f"WORKER{args.process_id}_RESULT "
          f"{results[-1]['train_loss']:.9f}", flush=True)


def launcher():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    procs = []
    logs = []
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    for i in range(2):
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count="
                      f"{DEVICES_PER_PROC}",
            PYTHONPATH=repo_root + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
        )
        # temp files, not PIPEs: an undrained pipe buffer would
        # deadlock a chatty worker against the poll loop below
        log = tempfile.TemporaryFile(mode="w+")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--process_id", str(i), "--num_processes", "2",
             "--coordinator", f"localhost:{port}"],
            env=env, stdout=log, stderr=subprocess.STDOUT))
    # one shared deadline; if any worker dies or stalls, kill the
    # peers too (a dead coordinator would otherwise hang its partner
    # in jax.distributed.initialize, orphaned past the test timeout)
    import time
    deadline = time.time() + 600
    pending = set(range(2))
    failed = False
    while pending and time.time() < deadline:
        for i in list(pending):
            rc = procs[i].poll()
            if rc is not None:
                pending.discard(i)
                failed = failed or rc != 0
        if failed:
            break
        time.sleep(0.5)
    if pending:
        for i in pending:
            procs[i].kill()
    outs = []
    for p, log in zip(procs, logs):
        p.wait(timeout=60)
        log.seek(0)
        outs.append(log.read())
        log.close()
    codes = [p.returncode for p in procs]
    results = []
    for i, out in enumerate(outs):
        for line in out.splitlines():
            if line.startswith(f"WORKER{i}_RESULT"):
                results.append(line.split()[1])
    if codes != [0, 0] or len(results) != 2:
        for i, out in enumerate(outs):
            sys.stderr.write(f"--- worker {i} (exit {codes[i]}) ---\n")
            sys.stderr.write(out[-4000:] + "\n")
        sys.exit(1)
    assert results[0] == results[1], \
        f"processes disagree: {results}"
    print(f"MULTIHOST_OK loss={results[0]}")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--process_id", type=int, default=None)
    ap.add_argument("--num_processes", type=int, default=2)
    ap.add_argument("--coordinator", type=str, default=None)
    args = ap.parse_args()
    if args.process_id is None:
        launcher()
    else:
        worker(args)
