"""Sketch-pipeline micro-bench: per-stage times at a given geometry.

Isolates the d-bound pieces of the federated sketch round (client
sketch, recovery estimates, selection, sparse resketch) so kernel work
can be attributed without a full-model xplane (VERDICT round-3 task #3
— the ~25 ms sketch constant at GPT-2 scale). ``--tree`` times
``sketch_from_leaves`` over a GPT-2-shaped leaf list against the flat
``sketch`` + its pad.

``--sketch_dtype {f32,bf16,int8,fp8}`` adds the wire-quantization
stages (quantize_table / dequantize / the fused sketch+quantize op)
and reports the uplink wire bytes next to the f32 reference, so one
invocation shows what a dtype buys in both time and bytes. With
``--ledger`` the result also lands as a bench record and a run
manifest under ``runs/`` (perf-gateable, wire-dtype keyed).

Usage:
  python scripts/sketch_bench.py [--d 124439808] [--c 524288] [--r 5]
      [--k 50000] [--reps 20] [--tree] [--sketch_dtype int8]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _force(out):
    """Force completion with a VALUE transfer: through the remote
    relay, block_until_ready can return before the (lazily compiled)
    program has even started — only materialising bytes on the host
    guarantees execution finished."""
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x.ravel()[:1] if hasattr(x, "ravel")
                             else x), out)


def timed(fn, *args, reps=20):
    out = fn(*args)
    _force(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _force(out)
    return (time.perf_counter() - t0) / reps * 1e3, out


def gpt2_like_shapes(d):
    """A leaf-shape list shaped like GPT-2 124M (embeddings + 12 x
    (attn + mlp + ln) + final ln), scaled so totals sum to d."""
    shapes = [(50257, 768), (1024, 768)]
    for _ in range(12):
        shapes += [(768,), (768,), (768, 2304), (2304,), (768, 768),
                   (768,), (768,), (768,), (768, 3072), (3072,),
                   (3072, 768), (768,)]
    shapes += [(768,), (768,)]
    total = sum(int(np.prod(s)) for s in shapes)
    if total > d:
        # small-d smoke: keep the leaf-count/size mix (one embedding-
        # like big leaf + interleaved matrices and vectors), scaled;
        # leaves whose scaled leading dim rounds to zero are DROPPED —
        # flooring them to one full row overshoots d at small scales
        scale = d / total
        shapes = [(int(s[0] * scale),) + tuple(s[1:]) for s in shapes]
        shapes = [s for s in shapes if s[0] > 0]
        total = sum(int(np.prod(s)) for s in shapes)
        assert total <= d, (total, d)
    if total < d:
        shapes.append((d - total,))
    return shapes


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--d", type=int, default=124_439_808)
    ap.add_argument("--c", type=int, default=524288)
    ap.add_argument("--r", type=int, default=5)
    ap.add_argument("--k", type=int, default=50000)
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--backend", default="auto")
    ap.add_argument("--rot_lanes", type=int, default=0)
    ap.add_argument("--tree", action="store_true")
    ap.add_argument("--chain", type=int, default=0,
                    help="also time N chained sketch->estimates "
                    "iterations inside ONE dispatch (fori_loop) — the "
                    "only reliable timing through the remote relay, "
                    "where per-dispatch latency swamps small ops")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU platform (the container's "
                    "sitecustomize overrides JAX_PLATFORMS)")
    ap.add_argument("--sketch_dtype", default="f32",
                    choices=["f32", "bf16", "int8", "fp8"],
                    help="also time the wire-quantization stages at "
                    "this dtype and report uplink wire bytes")
    ap.add_argument("--ledger", type=str, default="",
                    help="append the result as a telemetry JSONL "
                    "bench record and register a run manifest "
                    "(stdout line unchanged)")
    ap.add_argument("--autopilot", action="store_true",
                    help="also run the federated autopilot acceptance "
                    "leg: an 8-round CPU sketch loop launched at f32 "
                    "where the controller must converge to a >=2x "
                    "cheaper wire dtype with recovery error in band "
                    "every round (run under XLA_FLAGS=--xla_force_"
                    "host_platform_device_count=8 JAX_PLATFORMS=cpu)")
    ap.add_argument("--autopilot_band", default="0.05:0.6",
                    help="LO:HI recovery-error band for the "
                    "--autopilot leg (also keys its baseline pin)")
    ap.add_argument("--autopilot_rounds", type=int, default=8)
    ap.add_argument("--dp", action="store_true",
                    help="also run the DP acceptance leg: a federated "
                    "sketch loop with the full --dp sketch mechanism "
                    "armed (per-client clip + table noise at "
                    "sigma > 0) whose recovery error must hold the "
                    "--dp_band every probed round while the "
                    "accountant's eps grows monotonically")
    ap.add_argument("--dp_noise_mult", type=float, default=0.02,
                    help="noise multiplier for the --dp leg "
                    "(sigma > 0 is the point of the check)")
    ap.add_argument("--dp_band", default="0:0.9",
                    help="LO:HI recovery-error band for the --dp leg")
    ap.add_argument("--dp_rounds", type=int, default=8)
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from commefficient_tpu.ops.sketch import CountSketch
    from commefficient_tpu.ops.topk import threshold_topk_indices

    cs = CountSketch(d=args.d, c=args.c, r=args.r, seed=21,
                     backend=args.backend, rot_lanes=args.rot_lanes)
    rng = np.random.RandomState(0)
    v = jnp.asarray(rng.randn(args.d).astype(np.float32))
    res = {"geometry": {"d": args.d, "c": args.c, "r": args.r,
                        "k": args.k,
                        "backend": cs._resolve_backend()}}
    if jax.default_backend() in ("axon", "tpu"):
        # per-dispatch relay latency + value-transfer forcing swamp
        # individual ops (device.platform reports "tpu" through the
        # axon relay, so treat any TPU backend as relay-suspect);
        # only the single-dispatch chained number is a kernel
        # measurement here (see _force)
        res["note"] = ("remote/accelerator dispatch: per-op *_ms are "
                       "dispatch-dominated — trust chain_* only")

    ms, table = timed(jax.jit(cs.sketch), v, reps=args.reps)
    res["sketch_flat_ms"] = round(ms, 2)

    if args.tree:
        shapes = gpt2_like_shapes(args.d)
        leaves = []
        off = 0
        for s in shapes:
            n = int(np.prod(s))
            leaves.append(jax.device_put(
                jax.lax.dynamic_slice(v, (off,), (n,)).reshape(s)))
            off += n
        assert off == args.d, (off, args.d)

        fn = jax.jit(lambda ls: cs.sketch_from_leaves(ls))
        ms, table_t = timed(fn, leaves, reps=args.reps)
        res["sketch_from_leaves_ms"] = round(ms, 2)
        res["tables_equal"] = bool(jnp.array_equal(table, table_t))

    ms, est = timed(jax.jit(lambda t: cs.estimates(t, padded=True)),
                    table, reps=args.reps)
    res["estimates_padded_ms"] = round(ms, 2)
    ms, _ = timed(jax.jit(lambda t: cs.estimates(t)), table,
                  reps=args.reps)
    res["estimates_sliced_ms"] = round(ms, 2)

    ms, idx = timed(
        jax.jit(lambda e: threshold_topk_indices(jax.lax.square(e),
                                                 args.k)),
        est, reps=args.reps)
    res["threshold_select_ms"] = round(ms, 2)

    vals = est[idx]
    ms, _ = timed(jax.jit(cs.sketch_sparse), idx, vals,
                  reps=args.reps)
    res["sparse_resketch_ms"] = round(ms, 2)

    ms, _ = timed(jax.jit(lambda t, k=args.k: cs.unsketch(
        t, k, with_support=True, with_dense=False)), table,
        reps=args.reps)
    res["unsketch_sparse_total_ms"] = round(ms, 2)

    from commefficient_tpu import accounting
    wire = args.sketch_dtype
    res["wire"] = {
        "sketch_dtype": wire,
        "upload_wire_bytes": accounting.sketch_wire_bytes(
            args.r, args.c, wire),
        "upload_f32_bytes": accounting.sketch_wire_bytes(
            args.r, args.c, "f32"),
    }
    if wire != "f32":
        from commefficient_tpu.ops import quant
        ms, qs = timed(
            jax.jit(lambda t: quant.quantize_table(t, wire)),
            table, reps=args.reps)
        res["quantize_table_ms"] = round(ms, 2)
        q, scale = qs
        ms, _ = timed(
            jax.jit(lambda qq: quant.dequantize(qq, scale)), q,
            reps=args.reps)
        res["dequantize_ms"] = round(ms, 2)
        ms, _ = timed(
            jax.jit(lambda vv: cs.sketch_quantized(vv, wire)), v,
            reps=args.reps)
        res["sketch_quantized_fused_ms"] = round(ms, 2)

    if args.chain:
        n = args.chain

        @jax.jit
        def chained(v0):
            def body(i, carry):
                v, acc = carry
                t = cs.sketch(v)
                e = cs.estimates(t, padded=True)
                # feed the estimates back so no iteration is dead code
                return e[: args.d] * 0.999, acc + t[0, 0]
            v_out, acc = jax.lax.fori_loop(
                0, n, body, (v0, jnp.float32(0)))
            return acc + jnp.sum(v_out[:8])

        float(chained(v))  # value transfer = real warmup (see _force)
        t0 = time.perf_counter()
        out = chained(v)
        float(out)
        res["chain_sketch_plus_estimates_ms"] = round(
            (time.perf_counter() - t0) / n * 1e3, 2)

    ap_rec = ap_cfg = dp_cfg = None
    if args.autopilot:
        ap_res, ap_rec, ap_cfg = run_autopilot_leg(args)
        res["autopilot"] = ap_res
    if args.dp:
        dp_res, dp_cfg = run_dp_leg(args)
        res["dp"] = dp_res

    print(json.dumps(res))
    if args.ledger:
        from commefficient_tpu.telemetry import (append_bench_record,
                                                 registry)
        append_bench_record(args.ledger, "sketch_bench", res,
                            backend=jax.default_backend())
        if ap_cfg is not None:
            # manifest carries the FED config (autopilot + band) so
            # registry.run_band / run_wire_dtype key the pin from the
            # CONVERGED point, e.g. d8p1qint8b0.05-0.6
            registry.maybe_write_manifest(
                ap_cfg, bench={"sketch_bench": res},
                extra={"autopilot": ap_rec, "wire_dtype": wire})
        elif dp_cfg is not None:
            # DP leg: the manifest config carries dp/dp_epsilon so
            # the perf gate keys this pin under its privacy budget
            # (p<eps> fragment) — never comparable to a dp-off run
            registry.maybe_write_manifest(
                dp_cfg, bench={"sketch_bench": res},
                extra={"wire_dtype": wire})
        else:
            registry.maybe_write_manifest(
                args, bench={"sketch_bench": res},
                extra={"wire_dtype": wire})


def run_autopilot_leg(args):
    """The acceptance loop behind ``--autopilot``: a small federated
    sketch run (heavy-tailed synthetic gradients, probes every round)
    launched at f32 whose controller must walk to a cheaper wire while
    holding the recovery-error band. Returns ``(summary, record,
    cfg)`` — the record replays bit-exact via
    ``commefficient_tpu.autopilot.replay_record`` and rides the run
    manifest, and cfg (ledger attached) is what the manifest is keyed
    by."""
    from commefficient_tpu.autopilot import parse_band, replay_record
    from commefficient_tpu.config import Config
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)

    def loss(params, batch, cfg):
        pred = batch["x"] @ params["w"]
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
        return l, (l * 0.0 + 1.0,)

    W, B, d, num_clients = 4, 2, 512, 16
    cfg = Config(mode="sketch", error_type="virtual",
                 local_momentum=0.0, virtual_momentum=0.9,
                 num_workers=W, local_batch_size=B, seed=5,
                 num_clients=num_clients, k=64, num_rows=5,
                 num_cols=2048, sketch_dtype="f32", probe_every=1,
                 autopilot="on", autopilot_band=args.autopilot_band,
                 autopilot_cooldown=1, ledger=args.ledger)
    model = FedModel(None, {"w": jnp.zeros((d,), jnp.float32)},
                     loss, cfg, padded_batch_size=B)
    opt = FedOptimizer([{"lr": 0.25}], cfg, model=model)
    # power-law feature scaling -> heavy-tailed gradients, so top-k
    # recovery sits far below the dense-iid floor and the band has
    # room to hold across the dtype walk (same recipe as the tests)
    scale = (np.arange(1, d + 1) ** -1.5).astype(np.float32)
    rng = np.random.RandomState(5)
    t0 = time.perf_counter()
    for _ in range(args.autopilot_rounds):
        batch = {
            "client_ids": rng.choice(num_clients, W, replace=False)
            .astype(np.int32),
            "x": jnp.asarray(rng.randn(W, B, d).astype(np.float32)
                             * scale),
            "y": jnp.asarray(rng.randn(W, B), jnp.float32),
            "mask": jnp.ones((W, B), jnp.float32)}
        model(batch)
        opt.step()
    wall = time.perf_counter() - t0

    rec = model.autopilot_record()
    lo, hi = parse_band(args.autopilot_band)
    observed = [t for t in rec["trajectory"]
                if t["recovery_error"] is not None]
    counters = model._variants.counters()
    visited = {t["key"] for t in rec["trajectory"]}
    visited.add(rec["initial"])
    summary = {
        "rounds": args.autopilot_rounds,
        "band": args.autopilot_band,
        "initial": rec["initial"],
        "final": rec["final"],
        "initial_wire_bytes": rec["initial_wire_bytes"],
        "final_wire_bytes": rec["final_wire_bytes"],
        "uplink_reduction": round(
            rec["initial_wire_bytes"] / rec["final_wire_bytes"], 2),
        "band_held": bool(observed) and all(
            t["recovery_error"] <= hi for t in observed),
        "panics": sum(t["action"] == "panic"
                      for t in rec["trajectory"]),
        "variant_compiles": counters["misses"],
        "lattice_points_visited": len(visited),
        "compiles_within_visited": counters["misses"] <= len(visited),
        "replay_exact": replay_record(rec)
        == [t["key"] for t in rec["trajectory"]],
        "wall_s": round(wall, 2),
    }
    model.finalize()
    return summary, rec, cfg


def run_dp_leg(args):
    """The acceptance loop behind ``--dp``: the same small federated
    sketch run with the full ``--dp sketch`` mechanism armed —
    per-client L2 clip plus calibrated table noise at sigma > 0.
    Acceptance: every probed round's recovery error holds the
    ``--dp_band`` despite the noise, and the accountant's ε trail in
    the ledger is strictly increasing. Returns ``(summary, cfg)``;
    the summary's (sigma, recovery-error) pair is the BENCHMARKS
    noise-vs-recovery row, and cfg keys the run manifest under its
    privacy budget."""
    import tempfile

    from commefficient_tpu.autopilot import parse_band
    from commefficient_tpu.config import Config
    from commefficient_tpu.privacy import table_noise_std
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)

    def loss(params, batch, cfg):
        pred = batch["x"] @ params["w"]
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
        return l, (l * 0.0 + 1.0,)

    W, B, d, num_clients = 4, 2, 512, 16
    led = args.ledger
    tmpdir = None
    if not led:
        tmpdir = tempfile.mkdtemp(prefix="sketch_bench_dp_")
        led = os.path.join(tmpdir, "dp_ledger.jsonl")
    assert args.dp_noise_mult > 0, "--dp leg needs sigma > 0"
    cfg = Config(mode="sketch", error_type="virtual",
                 local_momentum=0.0, virtual_momentum=0.9,
                 num_workers=W, local_batch_size=B, seed=5,
                 num_clients=num_clients, k=64, num_rows=5,
                 num_cols=2048, probe_every=1, dp="sketch",
                 dp_clip=1.0, dp_noise_mult=args.dp_noise_mult,
                 dp_delta=1e-5, ledger=led)
    model = FedModel(None, {"w": jnp.zeros((d,), jnp.float32)},
                     loss, cfg, padded_batch_size=B)
    opt = FedOptimizer([{"lr": 0.25}], cfg, model=model)
    # shared-w_true regression (not iid noise targets): client
    # gradients ALIGN, so the aggregate keeps the per-client scale
    # and the noise-vs-signal ratio is set by the mechanism, not by
    # cross-client cancellation
    scale = (np.arange(1, d + 1) ** -1.5).astype(np.float32)
    rng = np.random.RandomState(5)
    w_true = rng.randn(d).astype(np.float32)
    t0 = time.perf_counter()
    for _ in range(args.dp_rounds):
        x = rng.randn(W, B, d).astype(np.float32) * scale
        batch = {
            "client_ids": rng.choice(num_clients, W, replace=False)
            .astype(np.int32),
            "x": jnp.asarray(x),
            "y": jnp.asarray(x.reshape(-1, d) @ w_true)
            .reshape(W, B),
            "mask": jnp.ones((W, B), jnp.float32)}
        model(batch)
        opt.step()
    wall = time.perf_counter() - t0
    model.finalize()

    # acceptance reads the LEDGER, not the model: the ε trail and
    # the probes must have survived all the way to the v5 records
    eps_traj, errs = [], []
    with open(led) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("kind") != "round":
                continue
            if isinstance(rec.get("dp_epsilon"), (int, float)):
                eps_traj.append(float(rec["dp_epsilon"]))
            rerr = (rec.get("probes") or {}).get("recovery_error")
            if isinstance(rerr, (int, float)):
                errs.append(float(rerr))
    lo, hi = parse_band(args.dp_band)
    summary = {
        "rounds": args.dp_rounds,
        "band": args.dp_band,
        "dp_noise_mult": args.dp_noise_mult,
        "table_noise_std": round(table_noise_std(cfg), 6),
        "eps_spent": eps_traj[-1] if eps_traj else None,
        "eps_monotone": all(b > a for a, b in
                            zip(eps_traj, eps_traj[1:])),
        "charged_rounds": len(eps_traj),
        "recovery_err_mean": (round(sum(errs) / len(errs), 4)
                              if errs else None),
        "recovery_err_max": (round(max(errs), 4) if errs else None),
        "band_held": bool(errs) and all(e <= hi for e in errs),
        "wall_s": round(wall, 2),
    }
    return summary, cfg


if __name__ == "__main__":
    main()
