"""GPT-2 flagship perf bench: federated FetchSGD round on GPT-2 124M.

Measures ms/round and tokens/s for the reference's LM workload
(gpt2_train.py round loop) at configurable batch geometry, with an
optional xplane profile parsed into a per-op time breakdown
(the only profiling recipe that works through this environment's
relay — see BENCHMARKS.md).

Usage:
  python scripts/gpt2_bench.py [--clients 4] [--examples 2]
      [--candidates 2] [--seq 256] [--rounds 10] [--remat]
      [--mode sketch|uncompressed|bare] [--profile DIR] [--reps 3]
  --mode bare = the non-federated control: plain pytree train step at
  the same geometry (no flat vector, no compression, no accounting).
"""

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def build(args):
    import dataclasses

    from commefficient_tpu.config import Config
    from commefficient_tpu.core.rounds import (ClientStates,
                                               build_client_round,
                                               build_server_round)
    from commefficient_tpu.core.server import ServerState
    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads
    from commefficient_tpu.ops.vec import flatten_params
    from commefficient_tpu.train.gpt2_train import \
        make_compute_loss_train

    cfg = Config(mode=args.mode, error_type="virtual",
                 local_momentum=0.0, virtual_momentum=0.9,
                 weight_decay=0.0, num_workers=args.clients,
                 local_batch_size=args.examples, k=50000, num_rows=5,
                 num_cols=524288, num_blocks=20,
                 dataset_name="PERSONA", seed=21,
                 approx_topk=not args.exact,
                 approx_recall=0.95, num_candidates=args.candidates,
                 lm_coef=1.0, mc_coef=1.0,
                 sketch_rot_lanes=args.rot_lanes,
                 tokens_per_chunk=args.tokens_per_chunk,
                 fused_ce=args.fused_ce)

    gcfg = GPT2Config(vocab_size=50262, n_positions=1024,
                      dtype=jnp.bfloat16, remat=args.remat,
                      attn_impl=args.attn_impl)
    module = GPT2DoubleHeads(gcfg)
    dummy = jnp.zeros((1, args.candidates, 8), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), dummy,
                         jnp.zeros((1, args.candidates), jnp.int32),
                         dummy)["params"]
    flat, unravel = flatten_params(params)
    cfg.grad_size = int(flat.size)

    compute_loss = make_compute_loss_train(module, cfg)

    def loss_tree(p, batch):
        return compute_loss(p, batch, cfg)

    client_round = jax.jit(build_client_round(
        cfg, None, args.examples,
        tree_loss=loss_tree, unravel=unravel))
    server_round = jax.jit(build_server_round(cfg))

    rng = np.random.RandomState(0)
    W, B, N, T = args.clients, args.examples, args.candidates, args.seq
    batch = {
        "input_ids": jnp.asarray(
            rng.randint(0, 50000, (W, B, N, T)), jnp.int32),
        "token_type_ids": jnp.asarray(
            rng.randint(0, 2, (W, B, N, T)), jnp.int32),
        "lm_labels": jnp.asarray(
            rng.randint(0, 50000, (W, B, N, T)), jnp.int32),
        "mc_token_ids": jnp.full((W, B, N), T - 1, jnp.int32),
        "mc_labels": jnp.full((W, B), N - 1, jnp.int32),
        "mask": jnp.ones((W, B), jnp.float32),
    }
    ids = jnp.arange(W, dtype=jnp.int32)
    cs = ClientStates.init(cfg, max(cfg.num_workers, 8), flat)
    ss = ServerState.init(cfg)
    key = jax.random.PRNGKey(0)

    @jax.jit
    def run_rounds(ps, ss):
        def body(r, carry):
            ps, ss = carry
            res = client_round(ps, cs, batch, ids,
                               jax.random.fold_in(key, r), 1.0)
            ps, ss, _, _, _ = server_round(ps, ss, res.aggregated,
                                           jnp.float32(0.01))
            return ps, ss

        ps, ss = jax.lax.fori_loop(0, args.rounds, body, (ps, ss))
        return ps, ss, jnp.sum(ps)

    return run_rounds, flat, ss, cfg


def build_bare(args):
    """Control experiment (round-2 review weak #7): the BARE model
    train step — no federation, no flat vector, no compression, no
    byte accounting. Same geometry, same loss math (chunked vocab CE),
    pytree params, momentum-SGD update. federated_overhead =
    federated ms/round − bare ms/step; if that matches the known
    sketch-pipeline constant, the \"42% MFU is the model's limit\"
    claim is a measurement, not an inference."""
    from commefficient_tpu.config import Config
    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads
    from commefficient_tpu.train.gpt2_train import \
        make_compute_loss_train

    cfg = Config(mode="uncompressed", error_type="none",
                 local_momentum=0.0, virtual_momentum=0.9,
                 weight_decay=0.0, num_workers=args.clients,
                 local_batch_size=args.examples,
                 dataset_name="PERSONA", seed=21,
                 num_candidates=args.candidates,
                 tokens_per_chunk=args.tokens_per_chunk,
                 fused_ce=args.fused_ce)
    gcfg = GPT2Config(vocab_size=50262, n_positions=1024,
                      dtype=jnp.bfloat16, remat=args.remat,
                      attn_impl=args.attn_impl)
    module = GPT2DoubleHeads(gcfg)
    dummy = jnp.zeros((1, args.candidates, 8), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), dummy,
                         jnp.zeros((1, args.candidates), jnp.int32),
                         dummy)["params"]
    compute_loss = make_compute_loss_train(module, cfg)

    rng = np.random.RandomState(0)
    W, B, N, T = args.clients, args.examples, args.candidates, args.seq
    E = W * B  # one flat batch: the W axis is just batch here
    batch = {
        "input_ids": jnp.asarray(
            rng.randint(0, 50000, (E, N, T)), jnp.int32),
        "token_type_ids": jnp.asarray(
            rng.randint(0, 2, (E, N, T)), jnp.int32),
        "lm_labels": jnp.asarray(
            rng.randint(0, 50000, (E, N, T)), jnp.int32),
        "mc_token_ids": jnp.full((E, N), T - 1, jnp.int32),
        "mc_labels": jnp.full((E,), N - 1, jnp.int32),
        "mask": jnp.ones((E,), jnp.float32),
    }

    def loss_fn(p):
        return compute_loss(p, batch, cfg)[0]

    grad_size = sum(int(np.prod(l.shape)) for l in
                    jax.tree_util.tree_leaves(params))
    cfg.grad_size = grad_size

    @jax.jit
    def run_rounds(params, mom):
        def body(r, carry):
            p, m = carry
            g = jax.grad(loss_fn)(p)
            m = jax.tree_util.tree_map(
                lambda mm, gg: 0.9 * mm + gg, m, g)
            p = jax.tree_util.tree_map(
                lambda pp, mm: pp - 0.01 * mm, p, m)
            return p, m

        p, m = jax.lax.fori_loop(0, args.rounds, body, (params, mom))
        checksum = sum(jnp.sum(l) for l in
                       jax.tree_util.tree_leaves(p)[:1])
        return p, m, checksum

    mom = jax.tree_util.tree_map(jnp.zeros_like, params)
    return run_rounds, params, mom, cfg


def parse_xplane(logdir):
    """Aggregate per-op durations from the trace's xplane.pb (the
    tensorboard converter is broken in this image)."""
    import glob
    import os
    os.environ["PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION"] = "python"
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(
        logdir, "**", "*.xplane.pb"), recursive=True)
    if not paths:
        return {}
    xspace = xplane_pb2.XSpace()
    with open(max(paths, key=os.path.getmtime), "rb") as f:
        xspace.ParseFromString(f.read())
    totals = {}
    for plane in xspace.planes:
        if "TPU" not in plane.name and "/device" not in plane.name:
            continue
        ev_meta = plane.event_metadata
        for line in plane.lines:
            for ev in line.events:
                name = ev_meta[ev.metadata_id].name
                totals[name] = totals.get(name, 0) \
                    + ev.duration_ps / 1e9  # ms
    return dict(sorted(totals.items(), key=lambda kv: -kv[1])[:40])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--examples", type=int, default=2)
    ap.add_argument("--candidates", type=int, default=2)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--exact", action="store_true",
                    help="exact top-k selection (the trainer default) "
                         "instead of approx_max_k 0.95")
    ap.add_argument("--mode", default="sketch")
    ap.add_argument("--attn_impl", default="xla",
                    choices=["xla", "flash"])
    ap.add_argument("--rot_lanes", type=int, default=-1,
                    help="-1 = the trainer's auto default (resolves "
                    "per backend/geometry, core/rounds.py "
                    "resolve_rot_lanes); 0 forces full-granularity "
                    "rotations for A/Bs against it")
    ap.add_argument("--fused_ce", default="off",
                    choices=["auto", "on", "off"],
                    help="fused-linear-CE vocab head (ops/"
                    "flce_pallas.py); auto = on at TPU backend")
    ap.add_argument("--tokens_per_chunk", type=int, default=0,
                    help="vocab-CE chunk budget (0 = auto 1024); the "
                    "task-5 sweep knob — larger chunks trade logits "
                    "VMEM/HBM for fewer dWte carry accumulations")
    ap.add_argument("--profile", type=str, default=None)
    ap.add_argument("--ledger", type=str, default="",
                    help="append the result as a telemetry JSONL "
                    "bench record (stdout line unchanged)")
    args = ap.parse_args()

    if args.mode == "bare":
        run_rounds, ps, ss, cfg = build_bare(args)
    else:
        run_rounds, ps, ss, cfg = build(args)

    w_ps, w_ss, w_sum = run_rounds(ps, ss)
    assert np.isfinite(float(w_sum)), "diverged/NaN in warmup"

    times = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        _, _, checksum = run_rounds(ps, ss)
        float(checksum)
        times.append(time.perf_counter() - t0)
    dt = sorted(times)[len(times) // 2]
    ms_round = dt / args.rounds * 1e3
    tokens = (args.clients * args.examples * args.candidates
              * args.seq)
    out = {
        "ms_per_round": round(ms_round, 2),
        "tokens_per_round": tokens,
        "tokens_per_sec": round(tokens / (ms_round / 1e3)),
        "clients_per_sec": round(args.clients / (ms_round / 1e3), 1),
        # 6 * params * tokens fwd+bwd FLOPs (approx, non-remat)
        "model_tflops_per_sec": round(
            6 * cfg.grad_size * tokens / (ms_round / 1e3) / 1e12, 1),
        "geometry": vars(args),
    }
    print(json.dumps(out))
    if args.ledger:
        from commefficient_tpu.telemetry import append_bench_record
        append_bench_record(args.ledger, "gpt2_bench", out,
                            backend=jax.default_backend())

    if args.profile:
        with jax.profiler.trace(args.profile):
            _, _, checksum = run_rounds(ps, ss)
            float(checksum)
        breakdown = parse_xplane(args.profile)
        per_round = {k: round(v / args.rounds, 3)
                     for k, v in breakdown.items()}
        print(json.dumps({"per_round_op_ms": per_round}, indent=1))


if __name__ == "__main__":
    main()
