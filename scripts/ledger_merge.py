"""Merge per-process ledger shards into one fleet ledger.

    python scripts/ledger_merge.py runs/a.jsonl
        discovers runs/a.jsonl.p1.jsonl, runs/a.jsonl.p2.jsonl, ...
        (the per-process shards telemetry/core.py writes on multi-host
        meshes) and writes runs/a.jsonl.merged.jsonl

Process 0 owns the canonical ledger — its round records carry the
replicated accounting arrays and the trace-derived device_time. Every
other process's shard carries what only THAT host observed: its
host-phase spans, RSS watermarks, locally-observed bytes, and (when
it traced) its own device_time. The merge joins shards on round id:

* each canonical round record gains ``shards`` —
  ``{"p<k>": {spans, counters, host_rss_peak_bytes, uplink_bytes,
  downlink_bytes, host_gap_s}}`` — plus ``host_gap_by_process``, the
  per-host host-gap seconds (the honest multi-host scoreboard: one
  host stalling shows up as ITS gap, not averaged away);
* shard rounds missing from the canonical ledger are appended in
  round order with ``shard_only: true`` (a host that kept going after
  process 0 died is data, not garbage);
* shard meta/bench/epoch records are dropped (the canonical copies
  are authoritative); the count is reported.

Per-JOB shards from a fedservice daemon run —
``runs/a.jsonl.job<j>.jsonl``, one solo-equivalent ledger per tenant
(telemetry/sinks.py ``job_ledger_path``) — are discovered alongside
the ``.p<k>`` process shards. Unlike process shards, job rounds are
INDEPENDENT round streams (round 3 of job 0 and round 3 of job 1 are
different rounds), so they cannot join on round id: every job record
is instead appended after the canonical stream stamped with
``"job": j``, rounds in order within each job. A multi-host daemon
produces the full matrix — ``runs/a.jsonl.job<j>.jsonl.p<k>.jsonl``
sub-shards per job — which are discovered per job shard and joined
on round id WITHIN the job (same rules as the top-level process
merge) before the job stream is appended.

Causal stitching (--causal_trace runs, schema v7): joined round
records union their ``causal`` spans across process shards (dedup by
deterministic span id), and after the merge the per-trace span DAGs
are reassembled (telemetry/causal.py ``assemble_traces``) — the
summary reports stitched trace/span counts and warns on any orphan
span (a parent id no shard supplied), which is how a torn shard or a
missing tenant trace shows up.

``scripts/telemetry_report.py`` renders merged ledgers with a
per-shard summary block. Pure host-side JSON work: no jax import.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from commefficient_tpu.telemetry.causal import assemble_traces  # noqa: E402
from commefficient_tpu.telemetry.record import validate_record  # noqa: E402

MERGED_SUFFIX = ".merged.jsonl"

#: round-record keys a shard contributes to the merged view (what the
#: observing process measured locally; device_time collapses to its
#: host-gap bucket)
SHARD_VIEW_KEYS = ("spans", "counters", "host_rss_peak_bytes",
                   "uplink_bytes", "downlink_bytes")


def discover_shards(path: str) -> list:
    """[(process_index, shard_path), ...] for a canonical ledger
    path, sorted by process index (telemetry/sinks.py
    shard_ledger_path layout)."""
    hits = []
    for shard in glob.glob(glob.escape(path) + ".p*.jsonl"):
        m = re.match(re.escape(path) + r"\.p(\d+)\.jsonl$", shard)
        if m:
            hits.append((int(m.group(1)), shard))
    return sorted(hits)


def discover_job_shards(path: str) -> list:
    """[(job_index, shard_path), ...] for a fedservice base ledger
    path, sorted by job index (telemetry/sinks.py job_ledger_path
    layout)."""
    hits = []
    for shard in glob.glob(glob.escape(path) + ".job*.jsonl"):
        m = re.match(re.escape(path) + r"\.job(\d+)\.jsonl$", shard)
        if m:
            hits.append((int(m.group(1)), shard))
    return sorted(hits)


def merge_job_shards(merged, job_records: dict) -> tuple:
    """Append per-job shard records to a merged stream, each stamped
    ``"job": j``. ``job_records``: {job_index: [records, ...]}.
    Returns (records, stats)."""
    out = list(merged)
    appended = 0
    for j, records in sorted(job_records.items()):
        for rec in records:
            rec = dict(rec)
            rec["job"] = int(j)
            out.append(rec)
            appended += 1
    stats = {"job_records": appended,
             "jobs": sorted(int(j) for j in job_records)}
    return out, stats


def load_records(path: str) -> tuple:
    """(records, problems) from one JSONL ledger; bad lines are
    skipped, not fatal."""
    records, problems = [], []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as exc:
                problems.append(f"{path}:{lineno}: not JSON ({exc})")
                continue
            issues = validate_record(rec)
            if issues:
                problems.append(
                    f"{path}:{lineno}: " + "; ".join(issues))
                continue
            records.append(rec)
    return records, problems


def _host_gap_s(rec):
    dt = rec.get("device_time")
    if isinstance(dt, dict):
        hg = dt.get("host_gap_s")
        if isinstance(hg, (int, float)):
            return hg
    return None


def _merge_causal(rec, shards: dict):
    """Union causal spans across process shards onto the (already
    copied) canonical round record's stamp, dedup'd by deterministic
    span id — each process carries the spans only IT observed; the
    joined record carries the round's whole DAG."""
    stamps = [rec.get("causal")]
    stamps += [sh.get("causal") for _, sh in sorted(shards.items())]
    stamps = [s for s in stamps if isinstance(s, dict)]
    if not stamps:
        return
    merged = dict(stamps[0])
    seen, spans = set(), []
    for stamp in stamps:
        for span in stamp.get("spans") or ():
            sid = span.get("id")
            if sid in seen:
                continue
            seen.add(sid)
            spans.append(span)
    merged["spans"] = spans
    rec["causal"] = merged


def _shard_view(rec) -> dict:
    view = {}
    for key in SHARD_VIEW_KEYS:
        if rec.get(key) is not None:
            view[key] = rec[key]
    hg = _host_gap_s(rec)
    if hg is not None:
        view["host_gap_s"] = hg
    return view


def merge_ledgers(canonical_records, shard_records: dict) -> tuple:
    """Join shard round records onto the canonical ones by round id.

    ``shard_records``: {process_index: [records, ...]}. Returns
    (merged_records, stats) where stats counts joined / shard-only
    rounds and dropped non-round shard records."""
    shard_rounds = {}       # round id -> {"p<k>": round record}
    dropped = 0
    for k, records in sorted(shard_records.items()):
        for rec in records:
            if rec.get("kind") == "round":
                shard_rounds.setdefault(
                    rec["round"], {})[f"p{int(k)}"] = rec
            else:
                dropped += 1
    merged, joined = [], 0
    seen_rounds = set()
    for rec in canonical_records:
        if rec.get("kind") != "round":
            merged.append(rec)
            continue
        ridx = rec["round"]
        seen_rounds.add(ridx)
        shards = shard_rounds.get(ridx)
        if not shards:
            merged.append(rec)
            continue
        joined += 1
        rec = dict(rec)
        rec["shards"] = {pk: _shard_view(sh)
                         for pk, sh in sorted(shards.items())}
        _merge_causal(rec, shards)
        gaps = {}
        hg0 = _host_gap_s(rec)
        if hg0 is not None:
            gaps["p0"] = hg0
        for pk, sh in sorted(shards.items()):
            hg = _host_gap_s(sh)
            if hg is not None:
                gaps[pk] = hg
        if gaps:
            rec["host_gap_by_process"] = gaps
        merged.append(rec)
    # rounds only a shard saw (e.g. process 0 died first): keep them,
    # flagged, in round order after the canonical stream
    orphans = []
    for ridx in sorted(set(shard_rounds) - seen_rounds):
        for pk, sh in sorted(shard_rounds[ridx].items()):
            orphan = dict(sh)
            orphan["shard_only"] = True
            orphans.append(orphan)
    merged.extend(orphans)
    stats = {"joined_rounds": joined, "shard_only_rounds": len(orphans),
             "dropped_shard_records": dropped,
             "shards": sorted(int(k) for k in shard_records)}
    return merged, stats


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-process ledger shards on round id")
    ap.add_argument("ledger",
                    help="canonical (process-0) ledger path; shards "
                         "are discovered as <ledger>.p<k>.jsonl")
    ap.add_argument("-o", "--out", default=None,
                    help=f"output path (default <ledger>{MERGED_SUFFIX})")
    args = ap.parse_args(argv)

    canonical, problems = load_records(args.ledger)
    shards = discover_shards(args.ledger)
    shard_records = {}
    for k, spath in shards:
        recs, probs = load_records(spath)
        shard_records[k] = recs
        problems.extend(probs)
    job_shards = discover_job_shards(args.ledger)
    job_records = {}
    job_subs = 0
    for j, jpath in job_shards:
        recs, probs = load_records(jpath)
        problems.extend(probs)
        # multi-host daemon: each job shard may have its own process
        # sub-shards (<base>.job<j>.jsonl.p<k>.jsonl) — join them on
        # round id WITHIN the job before appending the job stream
        subs = discover_shards(jpath)
        if subs:
            sub_records = {}
            for k, spath in subs:
                srecs, sprobs = load_records(spath)
                sub_records[k] = srecs
                problems.extend(sprobs)
            recs, substats = merge_ledgers(recs, sub_records)
            job_subs += len(subs)
            print(f"job {j}: joined {len(subs)} process "
                  f"sub-shard(s), {substats['joined_rounds']} "
                  f"round(s) joined, "
                  f"{substats['shard_only_rounds']} shard-only")
        job_records[j] = recs
    for p in problems:
        print(f"WARNING {p}", file=sys.stderr)
    if not shards and not job_shards:
        print(f"{args.ledger}: no shards found (expected "
              f"{args.ledger}.p<k>.jsonl or .job<j>.jsonl) — "
              "nothing to merge")
        return 1

    merged, stats = merge_ledgers(canonical, shard_records)
    merged, job_stats = merge_job_shards(merged, job_records)
    out = args.out or (args.ledger + MERGED_SUFFIX)
    with open(out, "w") as f:
        for rec in merged:
            json.dump(rec, f, separators=(",", ":"))
            f.write("\n")
    traces = assemble_traces(merged)
    if traces:
        n_spans = sum(len(t["spans"]) for t in traces.values())
        n_orphans = sum(len(t["orphans"]) for t in traces.values())
        print(f"causal: {len(traces)} trace(s), {n_spans} span(s) "
              f"stitched, {n_orphans} orphan(s)")
        for tid, t in sorted(traces.items()):
            if t["orphans"]:
                print(f"WARNING causal trace {tid}: orphan span(s) "
                      f"{t['orphans']} (parent id missing from "
                      "every shard)", file=sys.stderr)
    print(f"{args.ledger} + shards p{stats['shards']} "
          f"+ jobs {job_stats['jobs']}: "
          f"{stats['joined_rounds']} round(s) joined, "
          f"{stats['shard_only_rounds']} shard-only, "
          f"{job_stats['job_records']} job record(s) appended, "
          f"{stats['dropped_shard_records']} non-round shard "
          f"record(s) dropped -> {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
