#!/usr/bin/env bash
# GPT-2 / PersonaChat federated fine-tune at the reference defaults
# (gpt2_train.py:256 + utils.py:183-199: lr_scale 4e-2, local batch 8,
# 2 candidates, max_history 2, FetchSGD sketch compression). Place the
# personachat archive under $DATASET_DIR and the GPT-2 vocab +
# pytorch_model.bin under $MODEL_CHECKPOINT (zero-egress environment —
# nothing downloads). --num_cols 524288 is the lane-aligned twin of
# the reference's 500000 default: same compression ratio within 5%,
# and it engages the fused Pallas sketch kernels (BENCHMARKS.md).
# --approx_topk is the perf choice at GPT-2 scale (74 vs 105 ms/round);
# drop it for the exact reference-parity selection — since round 3 the
# exact path costs ~40% more instead of 7x (threshold select).
set -euo pipefail

DATASET_DIR=${DATASET_DIR:-./data/personachat}
MODEL_CHECKPOINT=${MODEL_CHECKPOINT:-./data/gpt2}

python -m commefficient_tpu.train.gpt2_train \
    --dataset_name PERSONA \
    --dataset_dir "$DATASET_DIR" \
    --model_checkpoint "$MODEL_CHECKPOINT" \
    --mode sketch \
    --error_type virtual \
    --local_momentum 0 \
    --virtual_momentum 0.9 \
    --num_workers 4 \
    --local_batch_size 8 \
    --valid_batch_size 8 \
    --num_candidates 2 \
    --max_history 2 \
    --num_epochs 3 \
    --lr_scale 4e-2 \
    --k 50000 \
    --num_rows 5 \
    --num_cols 524288 \
    --bf16 \
    --approx_topk \
    "$@"
