"""Cross-run performance regression gate (perf_baseline.json).

    python scripts/perf_gate.py --ledger runs/a.jsonl \
        --write-baseline perf_baseline.json      # capture a baseline
    python scripts/perf_gate.py --ledger runs/b.jsonl \
        --baseline perf_baseline.json --check    # gate a fresh run
    python scripts/perf_gate.py --runs_dir runs --check \
        --baseline perf_baseline.json            # gate the newest
                                                 # manifest-registered
                                                 # run

The committed baseline pins median + MAD per metric (host-span times,
schema-v3/v4 device-time buckets and skew stats, bench clients/s);
``--check`` fails — exit 1 — only outside a noise band of
``max(rel_tol x median, k x MAD)`` (telemetry/gate.py), so relay
jitter passes and a real regression cannot. ``--write-baseline`` over
an existing baseline first gates the new run against it and REFUSES
to re-baseline over a hard regression (``--force`` overrides, for
intentional trade-offs — the diff of perf_baseline.json is then the
reviewable artifact).

Baselines are **topology-keyed** (gate schema 2): the run's
``(device_count, process_count)`` — from its manifest, its ledger
meta record, or ``--device_count``/``--process_count`` — selects
which baseline entry gates it, and ``--write-baseline`` replaces ONLY
that entry. An 8-device run can therefore never be "compared" against
the single-chip baseline (the historical mis-comparison), and each
scaling-curve point (scripts/scaling_bench.py) is guarded
independently. Runs with unknown topology bucket under ``any``.

Pure host-side JSON work: no jax import, safe as a tier-1 CPU smoke.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

from commefficient_tpu.telemetry import gate, registry  # noqa: E402
from commefficient_tpu.telemetry.record import validate_record  # noqa: E402


def load_ledger_records(path):
    records = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                print(f"WARNING {path}:{lineno}: not JSON, skipped",
                      file=sys.stderr)
                continue
            if validate_record(rec):
                print(f"WARNING {path}:{lineno}: invalid record, "
                      "skipped", file=sys.stderr)
                continue
            records.append(rec)
    return records


def resolve_topology(manifest=None, records=(), device_count=None,
                     process_count=None, mesh_shape=None,
                     wire_dtype=None, async_k=None,
                     overlap_depth=None, band=None, dp_epsilon=None,
                     service_jobs=None):
    """The run's (device_count, process_count, mesh_shape,
    wire_dtype, async_k, overlap_depth) for baseline keying: CLI
    overrides win, then the run manifest, then the ledger's meta
    record (``num_devices``; pre-fleet metas never recorded a process
    count — those ran the single-process path, so 1). All-None when
    nothing knows — such runs gate under the ``any`` bucket.
    ``mesh_shape`` follows the same chain: a CLI "CxM" string, the
    manifest's recorded dict, or the meta record's ``mesh_shape``;
    1-D runs resolve to None (their key is the historical mesh-less
    one). ``wire_dtype`` likewise: CLI, the manifest config's
    ``sketch_dtype``, the meta record's round plan / cost model; f32
    and pre-quantization runs resolve to None (the historical
    unsuffixed key). ``async_k`` likewise: CLI, the manifest config's
    ``async_buffer_size``, the meta record's round plan; synchronous
    and pre-async runs resolve to None. ``overlap_depth`` likewise:
    CLI, the manifest config, the meta record's round plan; depth-1
    (serial) and pre-overlap runs resolve to None. ``band``
    likewise: a CLI "LO:HI" string, the manifest config's
    ``autopilot_band``, the meta record's round plan; static-knob
    runs resolve to None (no ``b<lo-hi>`` fragment). A band never
    falls back across bands: an autopilot run gates only against a
    baseline entry pinned under the SAME band — its wall profile
    mixes every knob point the controller visited, which no static
    pin describes. ``dp_epsilon`` likewise: a CLI float, the manifest
    config's ``dp_epsilon`` when ``dp`` != off, the meta record's
    ``plan.dp.epsilon_budget``; noiseless runs resolve to None (no
    ``p<eps>`` fragment) and a DP run with an unlimited budget keys
    ``p0``. A budget never falls back across budgets or to the
    noiseless pin: the calibrated table noise changes what the
    recovery probes measure. ``service_jobs`` likewise: a CLI int,
    the manifest's ``service_jobs`` stamp, the ledger meta record's
    ``service_jobs``; solo runs AND single-job daemon runs resolve to
    None (the daemon is bit-identical to the direct path at J=1, so
    the bare key is honest). A ``j<J>`` entry never falls back across
    J: a 3-tenant pod's aggregate throughput says nothing about a
    5-tenant one."""
    dc, pc = device_count, process_count
    ms = parse_mesh_shape(mesh_shape)
    wd = wire_dtype
    ak = async_k
    od = overlap_depth
    bd = band
    de = dp_epsilon
    sj = service_jobs
    if manifest is not None:
        mdc, mpc = registry.run_topology(manifest)
        dc = mdc if dc is None else dc
        pc = mpc if pc is None else pc
        if ms is None:
            ms = registry.run_mesh_shape(manifest)
        if wd is None:
            wd = registry.run_wire_dtype(manifest)
        if ak is None:
            ak = registry.run_async_k(manifest)
        if od is None:
            od = registry.run_overlap_depth(manifest)
        if bd is None:
            bd = registry.run_band(manifest)
        if de is None:
            de = registry.run_dp_epsilon(manifest)
        if sj is None:
            sj = registry.run_service_jobs(manifest)
    if dc is None or pc is None or ms is None or wd is None \
            or ak is None or od is None or bd is None \
            or de is None:
        for rec in records:
            if rec.get("kind") != "meta":
                continue
            if dc is None and rec.get("num_devices") is not None:
                dc = int(rec["num_devices"])
                if pc is None:
                    pc = int(rec.get("process_count") or 1)
            elif pc is None and rec.get("process_count") is not None:
                pc = int(rec["process_count"])
            if ms is None and isinstance(rec.get("mesh_shape"), dict):
                ms = dict(rec["mesh_shape"])
            plan = rec.get("plan") or {}
            if wd is None:
                cost = rec.get("cost_model") or {}
                if plan.get("mode") == "sketch":
                    wd = plan.get("sketch_dtype")
                elif cost.get("wire_dtype"):
                    wd = cost.get("wire_dtype")
            if ak is None and plan.get("async_buffer_size"):
                ak = int(plan["async_buffer_size"])
            if od is None and plan.get("overlap_depth"):
                od = int(plan["overlap_depth"])
            if bd is None and isinstance(plan.get("autopilot"), dict):
                bd = plan["autopilot"].get("band") or None
            if de is None and isinstance(plan.get("dp"), dict):
                # 0.0 is a real budget (unlimited) — "or None" would
                # erase the p0 fragment and let a DP ledger resolve
                # the noiseless pin
                eps = plan["dp"].get("epsilon_budget")
                de = float(eps) if eps is not None else 0.0
            if sj is None and rec.get("service_jobs") is not None:
                sj = int(rec["service_jobs"])
            if (dc is not None and pc is not None
                    and ms is not None and wd is not None
                    and ak is not None and od is not None
                    and bd is not None and de is not None):
                break
    if wd == "f32":
        wd = None  # historical unsuffixed key
    if not ak:
        ak = None  # synchronous runs keep the historical key
    if not od or int(od) <= 1:
        od = None  # serial rounds keep the historical key
    if not bd:
        bd = None  # static-knob runs keep the unbanded key
    if not sj or int(sj) <= 1:
        sj = None  # solo / single-job-daemon runs keep the bare key
    return dc, pc, ms, wd, ak, od, bd, de, sj


def parse_mesh_shape(mesh_shape):
    """"CxM" -> {"clients": C, "model": M}; dicts/None pass through."""
    if mesh_shape is None or isinstance(mesh_shape, dict):
        return mesh_shape
    c, m = (int(p) for p in str(mesh_shape).lower().split("x"))
    return {"clients": c, "model": m}


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="perf regression gate over telemetry ledgers")
    ap.add_argument("--ledger", default=None,
                    help="run ledger (JSONL) to gate / baseline")
    ap.add_argument("--runs_dir", default=None,
                    help="discover the newest manifest-registered "
                         "ledger under this directory instead of "
                         "--ledger")
    ap.add_argument("--baseline", default="perf_baseline.json",
                    help="committed baseline JSON (default "
                         "perf_baseline.json)")
    ap.add_argument("--check", action="store_true",
                    help="gate the run against --baseline; exit 1 on "
                         "any hard regression")
    ap.add_argument("--write-baseline", metavar="PATH", nargs="?",
                    const="perf_baseline.json", default=None,
                    help="write the run's metrics as the new baseline "
                         "(refused over a hard regression vs the "
                         "existing one unless --force)")
    ap.add_argument("--force", action="store_true",
                    help="re-baseline even over a regression")
    ap.add_argument("--rel_tol", type=float, default=gate.REL_TOL,
                    help="relative tolerance component of the noise "
                         f"band (default {gate.REL_TOL})")
    ap.add_argument("--mad_k", type=float, default=gate.MAD_K,
                    help="MAD multiples component of the noise band "
                         f"(default {gate.MAD_K})")
    ap.add_argument("--json", default=None,
                    help="dump the verdict (or captured metrics) to "
                         "this path")
    ap.add_argument("--device_count", type=int, default=None,
                    help="override the run's device count for "
                         "baseline keying (normally read from the "
                         "manifest / ledger meta)")
    ap.add_argument("--process_count", type=int, default=None,
                    help="override the run's process count for "
                         "baseline keying")
    ap.add_argument("--mesh_shape", default=None,
                    help="override the run's 2D mesh layout "
                         "(\"CxM\", e.g. 4x2) for baseline keying "
                         "(normally read from the manifest / ledger "
                         "meta; 1-D runs need nothing)")
    ap.add_argument("--wire_dtype", default=None,
                    help="override the run's uplink wire dtype "
                         "(f32/bf16/int8/fp8) for baseline keying "
                         "(normally read from the manifest config / "
                         "ledger meta; f32 runs keep the historical "
                         "unsuffixed key)")
    ap.add_argument("--async_k", type=int, default=None,
                    help="override the run's --async_buffer_size for "
                         "baseline keying (normally read from the "
                         "manifest config / ledger meta plan; "
                         "synchronous runs keep the historical "
                         "unsuffixed key)")
    ap.add_argument("--overlap_depth", type=int, default=None,
                    help="override the run's --overlap_depth for "
                         "baseline keying (normally read from the "
                         "manifest config / ledger meta plan; "
                         "depth-1 serial runs keep the historical "
                         "unsuffixed key)")
    ap.add_argument("--band", default=None,
                    help="override the run's --autopilot_band "
                         "(\"LO:HI\") for baseline keying (normally "
                         "read from the manifest config / ledger "
                         "meta plan; static-knob runs keep the "
                         "unbanded key). Banded entries NEVER gate "
                         "against another band or an unbanded pin.")
    ap.add_argument("--dp_epsilon", type=float, default=None,
                    help="override the run's --dp_epsilon privacy "
                         "budget for baseline keying (normally read "
                         "from the manifest config / ledger meta "
                         "plan; noiseless runs keep the unsuffixed "
                         "key, a DP run with no budget cap keys p0). "
                         "Private entries NEVER gate against another "
                         "budget or a noiseless pin.")
    ap.add_argument("--service_jobs", type=int, default=None,
                    help="override the run's fedservice tenant count "
                         "for baseline keying (normally read from "
                         "the manifest / ledger meta; solo and "
                         "single-job daemon runs keep the bare key). "
                         "j<J> entries NEVER gate across J.")
    args = ap.parse_args(argv)

    ledger = args.ledger
    manifest = None
    if ledger is None and args.runs_dir:
        hits = registry.latest_ledgers(args.runs_dir, n=1)
        if not hits:
            print(f"no manifest-registered ledgers under "
                  f"{args.runs_dir}")
            return 1
        mpath, manifest, ledger = hits[0]
        dc, pc = registry.run_topology(manifest)
        print(f"run: {mpath} (config {manifest.get('config_hash', '')[:8]}, "
              f"git {manifest.get('git_sha', '')[:8]}, "
              f"topology "
              f"{gate.topology_key(dc, pc, registry.run_mesh_shape(manifest), registry.run_wire_dtype(manifest), registry.run_async_k(manifest), registry.run_overlap_depth(manifest), registry.run_band(manifest), registry.run_dp_epsilon(manifest), registry.run_service_jobs(manifest))}"
              f") -> {ledger}")
    if ledger is None:
        ap.error("one of --ledger / --runs_dir is required")

    records = load_ledger_records(ledger)
    metrics = gate.metrics_from_records(records)
    if not metrics:
        print(f"{ledger}: no gateable metrics (empty ledger?)")
        return 1
    dc, pc, ms, wd, ak, od, bd, de, sj = resolve_topology(
        manifest, records, args.device_count, args.process_count,
        args.mesh_shape, args.wire_dtype, args.async_k,
        args.overlap_depth, args.band, args.dp_epsilon,
        args.service_jobs)
    topo = gate.topology_key(dc, pc, ms, wd, ak, od, bd, de, sj)
    print(f"{ledger}: {len(metrics)} metric(s) extracted "
          f"(topology {topo})")
    chash = (manifest or {}).get("config_hash", "")

    # a run that resized mid-run (elastic resume onto a different
    # topology) has a ledger that mixes rounds measured under
    # DIFFERENT topologies — no single baseline entry is a valid pin
    # for it, in either direction
    if manifest is not None and registry.run_topology_changed(manifest):
        segs = registry.run_segments(manifest)
        chain = " -> ".join(
            gate.topology_key(s.get("device_count"),
                              s.get("process_count"),
                              s.get("mesh_shape"), wd, ak, od, bd,
                              de, sj)
            for s in segs)
        print(f"perf gate: REFUSED — run resumed across a mid-run "
              f"topology change ({len(segs)} segments: {chain}); its "
              "metrics span topologies and never resolve to one "
              "baseline pin. Gate each segment's own ledger instead.")
        if args.check or args.write_baseline:
            return 1
        return 0

    verdict = None
    existing = None
    # a write-only invocation gates against the file it is about to
    # overwrite; --check gates against the committed --baseline
    gate_path = (args.write_baseline
                 if args.write_baseline and not args.check
                 else args.baseline)
    if args.check or (args.write_baseline
                      and os.path.exists(gate_path)
                      and not args.force):
        if not os.path.exists(gate_path):
            print(f"baseline {gate_path} missing — capture one "
                  "with --write-baseline first")
            return 1
        existing = gate.load_baseline(gate_path)
        entry = gate.baseline_entry(existing, dc, pc, ms, wd, ak, od,
                                    bd, de, sj)
        if entry is None and args.write_baseline and not args.check:
            # first capture of a NEW topology point: nothing to gate
            # this run against, other points stay untouched
            print(f"baseline has no {topo} entry yet — capturing it")
        elif entry is None:
            print(f"perf gate: FAIL — baseline {args.baseline} has "
                  f"no {topo} entry (this topology point is ungated; "
                  "capture one with --write-baseline)")
            return 1
        else:
            if entry is not None and chash and \
                    entry.get("config_hash") and \
                    entry["config_hash"] != chash:
                print(f"WARNING: baseline {topo} entry was captured "
                      f"from config {entry['config_hash'][:8]}, run "
                      f"is {chash[:8]} — metrics may not be "
                      "comparable")
            verdict = gate.compare(existing, metrics,
                                   rel_tol=args.rel_tol,
                                   mad_k=args.mad_k,
                                   device_count=dc, process_count=pc,
                                   mesh_shape=ms, wire_dtype=wd,
                                   async_k=ak, overlap_depth=od,
                                   band=bd, dp_epsilon=de,
                                   service_jobs=sj)
            print(gate.render_verdict(verdict))

    if args.write_baseline:
        if verdict and verdict["regressions"] and not args.force:
            print(f"\nNOT writing {args.write_baseline}: "
                  f"{len(verdict['regressions'])} hard regression(s) "
                  "vs the existing baseline — fix them or pass "
                  "--force for an intentional trade-off")
            return 1
        if existing is None and os.path.exists(args.write_baseline):
            existing = gate.load_baseline(args.write_baseline)
        gate.save_baseline(
            gate.update_baseline(existing or {}, metrics,
                                 source=os.path.abspath(ledger),
                                 device_count=dc, process_count=pc,
                                 config_hash=chash, mesh_shape=ms,
                                 wire_dtype=wd, async_k=ak,
                                 overlap_depth=od, band=bd,
                                 dp_epsilon=de, service_jobs=sj),
            args.write_baseline)
        print(f"baseline[{topo}] -> {args.write_baseline}")

    if args.json:
        with open(args.json, "w") as f:
            json.dump(verdict if verdict is not None else metrics, f,
                      indent=1, sort_keys=True)
        print(f"verdict -> {args.json}")

    if args.check and verdict and verdict["regressions"]:
        print(f"\nperf gate: FAIL "
              f"({len(verdict['regressions'])} regression(s))")
        return 1
    if args.check:
        print("\nperf gate: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
