"""Cross-run performance regression gate (perf_baseline.json).

    python scripts/perf_gate.py --ledger runs/a.jsonl \
        --write-baseline perf_baseline.json      # capture a baseline
    python scripts/perf_gate.py --ledger runs/b.jsonl \
        --baseline perf_baseline.json --check    # gate a fresh run
    python scripts/perf_gate.py --runs_dir runs --check \
        --baseline perf_baseline.json            # gate the newest
                                                 # manifest-registered
                                                 # run

The committed baseline pins median + MAD per metric (host-span times,
schema-v3 device-time buckets, bench clients/s); ``--check`` fails —
exit 1 — only outside a noise band of ``max(rel_tol x median, k x
MAD)`` (telemetry/gate.py), so relay jitter passes and a real
regression cannot. ``--write-baseline`` over an existing baseline
first gates the new run against it and REFUSES to re-baseline over a
hard regression (``--force`` overrides, for intentional trade-offs —
the diff of perf_baseline.json is then the reviewable artifact).

Pure host-side JSON work: no jax import, safe as a tier-1 CPU smoke.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

from commefficient_tpu.telemetry import gate, registry  # noqa: E402
from commefficient_tpu.telemetry.record import validate_record  # noqa: E402


def load_ledger_records(path):
    records = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                print(f"WARNING {path}:{lineno}: not JSON, skipped",
                      file=sys.stderr)
                continue
            if validate_record(rec):
                print(f"WARNING {path}:{lineno}: invalid record, "
                      "skipped", file=sys.stderr)
                continue
            records.append(rec)
    return records


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="perf regression gate over telemetry ledgers")
    ap.add_argument("--ledger", default=None,
                    help="run ledger (JSONL) to gate / baseline")
    ap.add_argument("--runs_dir", default=None,
                    help="discover the newest manifest-registered "
                         "ledger under this directory instead of "
                         "--ledger")
    ap.add_argument("--baseline", default="perf_baseline.json",
                    help="committed baseline JSON (default "
                         "perf_baseline.json)")
    ap.add_argument("--check", action="store_true",
                    help="gate the run against --baseline; exit 1 on "
                         "any hard regression")
    ap.add_argument("--write-baseline", metavar="PATH", nargs="?",
                    const="perf_baseline.json", default=None,
                    help="write the run's metrics as the new baseline "
                         "(refused over a hard regression vs the "
                         "existing one unless --force)")
    ap.add_argument("--force", action="store_true",
                    help="re-baseline even over a regression")
    ap.add_argument("--rel_tol", type=float, default=gate.REL_TOL,
                    help="relative tolerance component of the noise "
                         f"band (default {gate.REL_TOL})")
    ap.add_argument("--mad_k", type=float, default=gate.MAD_K,
                    help="MAD multiples component of the noise band "
                         f"(default {gate.MAD_K})")
    ap.add_argument("--json", default=None,
                    help="dump the verdict (or captured metrics) to "
                         "this path")
    args = ap.parse_args(argv)

    ledger = args.ledger
    if ledger is None and args.runs_dir:
        hits = registry.latest_ledgers(args.runs_dir, n=1)
        if not hits:
            print(f"no manifest-registered ledgers under "
                  f"{args.runs_dir}")
            return 1
        mpath, manifest, ledger = hits[0]
        print(f"run: {mpath} (config {manifest.get('config_hash', '')[:8]}, "
              f"git {manifest.get('git_sha', '')[:8]}) -> {ledger}")
    if ledger is None:
        ap.error("one of --ledger / --runs_dir is required")

    records = load_ledger_records(ledger)
    metrics = gate.metrics_from_records(records)
    if not metrics:
        print(f"{ledger}: no gateable metrics (empty ledger?)")
        return 1
    print(f"{ledger}: {len(metrics)} metric(s) extracted")

    verdict = None
    if args.check or (args.write_baseline
                      and os.path.exists(args.baseline)
                      and not args.force):
        if not os.path.exists(args.baseline):
            print(f"baseline {args.baseline} missing — capture one "
                  "with --write-baseline first")
            return 1
        baseline = gate.load_baseline(args.baseline)
        verdict = gate.compare(baseline, metrics,
                               rel_tol=args.rel_tol,
                               mad_k=args.mad_k)
        print(gate.render_verdict(verdict))

    if args.write_baseline:
        if verdict and verdict["regressions"] and not args.force:
            print(f"\nNOT writing {args.write_baseline}: "
                  f"{len(verdict['regressions'])} hard regression(s) "
                  "vs the existing baseline — fix them or pass "
                  "--force for an intentional trade-off")
            return 1
        gate.save_baseline(
            gate.make_baseline(metrics, source=os.path.abspath(ledger)),
            args.write_baseline)
        print(f"baseline -> {args.write_baseline}")

    if args.json:
        with open(args.json, "w") as f:
            json.dump(verdict if verdict is not None else metrics, f,
                      indent=1, sort_keys=True)
        print(f"verdict -> {args.json}")

    if args.check and verdict and verdict["regressions"]:
        print(f"\nperf gate: FAIL "
              f"({len(verdict['regressions'])} regression(s))")
        return 1
    if args.check:
        print("\nperf gate: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
