"""Topology scaling curves: one config swept across device counts.

    python scripts/scaling_bench.py                    # 1 -> 2 -> 4
    python scripts/scaling_bench.py --device_counts 1,2,4,8 \
        --rounds 8 --runs_dir runs
    python scripts/scaling_bench.py --multihost        # adds a
                                                       # 2-process point
    python scripts/scaling_bench.py --device_counts 1,8 \
        --mesh_shapes 8x1,4x2,2x4,1x8                  # 2D-mesh sweep

Each point runs the SAME small FetchSGD round workload (so every
manifest shares one config hash) in a fresh subprocess pinned to N
virtual CPU devices (``--xla_force_host_platform_device_count`` — the
device count is frozen at backend init, hence one process per point;
on a real pod, run the worker once per slice topology instead). Every
point is profiled, so its ledger carries schema-v4 per-device buckets
and collective-skew stats, and writes one run-registry manifest with a
top-level ``scaling`` block:

    {"device_count", "process_count", "clients_per_s",
     "parallel_efficiency", "collective_fraction", "max_skew_s"}

``parallel_efficiency`` is per-device throughput relative to the
smallest point ((tput_N / N) / (tput_ref / N_ref)): 1.0 is linear
scaling, the gap to 1.0 is what the collective fraction + skew columns
explain. ``scripts/telemetry_report.py --runs_dir runs`` renders the
curve; ``scripts/perf_gate.py`` gates each point against its own
topology-keyed baseline entry.

``--mesh_shapes`` appends one point per 2D (clients x model) mesh
layout (core/rounds 2D round: column-sharded sketch table,
reduce-scatter emission, distributed top-k select). Each shape gets
its own manifest whose ``mesh_shape`` extends the perf-gate topology
key (``d<D>p<P>m<C>x<M>``), so a 4x2 point and a 2x4 point on the
same 8 chips are guarded independently. Shapes whose device product
exceeds the host's cores still run — virtual CPU devices make e.g. a
32-device ``8x4`` layout a (slow but honest) dryrun.

``--sketch_dtypes`` appends one point per uplink wire dtype
(bf16/int8/fp8) on the largest requested device count. Each point's
config carries its ``--sketch_dtype``, so its manifest — and
therefore its perf-gate topology key — gets the ``q<dtype>`` suffix
(``d8p1qint8``): a quantized point is guarded by its own baseline
entry and never compared against the f32 curve.

``--overlap_depths`` appends one point per round-pipeline chunk
depth (e.g. ``1,2,4``) on the largest requested device count, each
with ``--sketch_dtype int8`` (the wire the pipeline is built to
hide). Each point's config carries its ``--overlap_depth``, so its
manifest — and therefore its perf-gate topology key — gets the
``o<N>`` suffix for depths > 1 (``d8p1qint8o2``): a pipelined point
is guarded by its own baseline entry, never compared across depths.
The point's ``overlapped_fraction`` column (hidden collective time
over total collective time, from the ledger's ``overlapped_s``
buckets) is the headline the sweep exists to show.

``--resize CxM:C2xM2`` appends an elastic-resume pair: the workload
runs on the first mesh, checkpoints, and the SAME run resumes on the
second mesh (a different device count) — the resumed point's
``scaling`` block carries ``resume_load_s`` (the restore wall time)
and its manifest the ``resumed_from`` + ``topology_segments`` lineage
stamps, so the report renders the pair as one lineage and the perf
gate refuses to pin the topology-spanning ledger.

``--multihost`` appends a 2-process point via the
scripts/multihost_smoke.py launcher pattern (free-port coordinator,
``jax.distributed.initialize`` per worker): process 0 writes the
canonical ledger + manifest, process 1 writes a ledger shard, and the
parent merges them with scripts/ledger_merge.py — the end-to-end
fleet-observatory path on one machine.
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

POINT_TAG = "SCALING_POINT "

# one shared workload geometry: 8 workers so every device count in
# {1, 2, 4, 8} divides it, tiny dense model + sketch so a point is
# seconds, not minutes, on CPU
W, B, DIM, ROUNDS_DEFAULT = 8, 4, 32, 5


def worker(args):
    import jax

    jax.config.update("jax_platforms", "cpu")
    if args.coordinator:
        jax.distributed.initialize(args.coordinator,
                                   num_processes=args.num_processes,
                                   process_id=args.process_id)
    assert jax.device_count() == args.devices, \
        (jax.device_count(), args.devices)

    import flax.linen as nn
    import jax.numpy as jnp
    import numpy as np

    from commefficient_tpu.config import Config
    from commefficient_tpu.runtime import FedModel, FedOptimizer
    from commefficient_tpu.runtime.checkpoint import (
        load_checkpoint, resume_manifest_extra, save_checkpoint)
    from commefficient_tpu.telemetry import clock, registry
    from commefficient_tpu.telemetry.profiler import trace_window

    class Lin(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(64, use_bias=False)(x)

    module = Lin()
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, DIM)))["params"]
    cfg = Config(mode="sketch", error_type="virtual",
                 local_momentum=0.0, virtual_momentum=0.9,
                 num_workers=W, local_batch_size=B,
                 num_clients=W * 2, dataset_name="CIFAR10", seed=0,
                 k=16, num_rows=3, num_cols=256, mesh=args.mesh,
                 sketch_dtype=args.sketch_dtype,
                 overlap_depth=args.overlap_depth)
    cfg.ledger = args.ledger
    cfg.do_profile = True

    def loss(p, batch, _cfg):
        pred = module.apply({"params": p}, batch["x"])
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        return jnp.sum(pred ** 2 * batch["mask"][..., None]) / n, ()

    model = FedModel(module, params, loss, cfg, padded_batch_size=B)
    opt = FedOptimizer([{"lr": 0.1}], cfg)
    resume_load_s = 0.0
    if args.ckpt_resume:
        # elastic resume point: restore the partner point's state
        # onto THIS mesh before any timed work — the load wall time
        # is the headline resume cost
        t_load = clock.tick()
        load_checkpoint(args.ckpt_resume, model, opt)
        resume_load_s = clock.tick() - t_load
    rng = np.random.RandomState(0)  # same seed on every process: SPMD

    def mk(r):
        return {"x": rng.randn(W, B, DIM).astype(np.float32),
                "y": rng.randn(W, B).astype(np.float32),
                "mask": np.ones((W, B), np.float32),
                "client_ids": np.arange(r, r + W, dtype=np.int32)
                % (W * 2)}

    model(mk(0))  # round 0 outside the window: compile/warmup
    opt.step()
    logdir = os.path.join(tempfile.mkdtemp(prefix="scaling_"), "trace")
    with trace_window(logdir, telemetry=model.telemetry):
        t0 = clock.tick()
        for r in range(1, args.rounds + 1):
            model(mk(r))
            opt.step()
        jax.block_until_ready(model.ps_weights)
        dt = clock.tick() - t0
    if args.ckpt_save:
        save_checkpoint(args.ckpt_save, model, opt)
    model.finalize()

    if jax.process_index() != 0:
        return 0

    clients_per_s = W * args.rounds / dt
    # parallel efficiency vs the reference (smallest) point: how much
    # of each added device's throughput the topology actually keeps
    if args.ref_clients_per_s > 0:
        eff = ((clients_per_s / args.devices)
               / (args.ref_clients_per_s / args.ref_devices))
    else:
        eff = 1.0

    # the ledger this run just wrote explains the curve: collective
    # fraction of the round window + worst straggler skew + how much
    # of the collective time the chunk pipeline hid under compute
    coll_fracs, skews = [], []
    coll_total, ovl_total = 0.0, 0.0
    with open(args.ledger) as f:
        for line in f:
            rec = json.loads(line)
            dt_rec = rec.get("device_time") if rec.get(
                "kind") == "round" else None
            if not dt_rec:
                continue
            if dt_rec.get("window_s"):
                coll_fracs.append(dt_rec.get("collective_s", 0.0)
                                  / dt_rec["window_s"])
            coll_total += dt_rec.get("collective_s", 0.0)
            ovl_total += dt_rec.get("overlapped_s", 0.0)
            skew = dt_rec.get("skew") or {}
            if skew.get("max_enter_delta_s") is not None:
                skews.append(skew["max_enter_delta_s"])
    mesh_shape = {str(k): int(v)
                  for k, v in dict(model.mesh.shape).items()}
    point = {
        "device_count": int(jax.device_count()),
        "process_count": int(jax.process_count()),
        "mesh_shape": mesh_shape,
        "sketch_dtype": args.sketch_dtype,
        "overlap_depth": int(args.overlap_depth),
        "overlapped_fraction": round(ovl_total / coll_total, 4)
        if coll_total > 0 else 0.0,
        "upload_wire_bytes_per_client": float(
            cfg.upload_wire_bytes_per_client),
        "clients_per_s": round(clients_per_s, 2),
        "parallel_efficiency": round(eff, 3),
        "collective_fraction": round(
            sum(coll_fracs) / len(coll_fracs), 4) if coll_fracs
        else 0.0,
        "max_skew_s": round(max(skews), 6) if skews else 0.0,
    }
    if args.ckpt_resume:
        point["resume_load_s"] = round(resume_load_s, 4)
    manifest = registry.write_manifest(
        args.runs_dir, args=cfg, ledger=args.ledger,
        bench={"clients_per_s": {"value": point["clients_per_s"],
                                 "unit": "clients/s"}},
        mesh_shape=mesh_shape,
        extra={"scaling": point, **resume_manifest_extra(model)})
    print(POINT_TAG + json.dumps(point), flush=True)
    print(f"manifest -> {manifest}", file=sys.stderr)
    return 0


def _run_point(n, args, ref, stamp, extra_cmd=(), extra_env=None,
               nproc=1, tag=""):
    """Spawn worker subprocess(es) for one topology point; returns
    (point dict, ledger path) or raises RuntimeError. ``tag``
    disambiguates same-device-count points (two mesh shapes on the
    same chip count must not share a ledger)."""
    os.makedirs(os.path.join(args.runs_dir, "scaling"), exist_ok=True)
    ledger = os.path.join(args.runs_dir, "scaling",
                          f"scale_{stamp}_d{n}p{nproc}{tag}.jsonl")
    dpp = n // nproc
    cmd = [sys.executable, os.path.abspath(__file__), "--worker",
           "--devices", str(n), "--rounds", str(args.rounds),
           "--runs_dir", args.runs_dir, "--ledger", ledger]
    if ref is not None:
        cmd += ["--ref_clients_per_s", str(ref[0]),
                "--ref_devices", str(ref[1])]
    cmd += list(extra_cmd)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count="
                         f"{dpp}",
               PYTHONPATH=REPO_ROOT + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.update(extra_env or {})
    if nproc == 1:
        out = subprocess.run(cmd, env=env, capture_output=True,
                             text=True, timeout=args.timeout)
        outs, codes = [out.stdout + out.stderr], [out.returncode]
    else:
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        procs, logs = [], []
        for i in range(nproc):
            log = tempfile.TemporaryFile(mode="w+")
            logs.append(log)
            procs.append(subprocess.Popen(
                cmd + ["--process_id", str(i),
                       "--num_processes", str(nproc),
                       "--coordinator", f"localhost:{port}"],
                env=env, stdout=log, stderr=subprocess.STDOUT))
        deadline = time.time() + args.timeout
        while any(p.poll() is None for p in procs) \
                and time.time() < deadline:
            # a dead coordinator hangs its partner in
            # jax.distributed.initialize: kill the survivors
            if any(p.poll() not in (None, 0) for p in procs):
                break
            time.sleep(0.5)
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=60)
        outs = []
        for log in logs:
            log.seek(0)
            outs.append(log.read())
            log.close()
        codes = [p.returncode for p in procs]
    if nproc > 1 and any(
            "Multiprocess computations aren't implemented" in out
            for out in outs):
        # this jaxlib's CPU backend cannot run cross-process
        # computations (same limitation hits
        # scripts/multihost_smoke.py) — skip the point instead of
        # failing so the single-process curve still lands
        return None, ledger
    point = None
    for out in outs:
        for line in out.splitlines():
            if line.startswith(POINT_TAG):
                point = json.loads(line[len(POINT_TAG):])
    if any(codes) or point is None:
        for i, out in enumerate(outs):
            sys.stderr.write(f"--- point d{n}p{nproc} worker {i} "
                             f"(exit {codes[i]}) ---\n")
            sys.stderr.write(out[-4000:] + "\n")
        raise RuntimeError(f"scaling point d{n}p{nproc} failed")
    return point, ledger


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="sweep one config across device counts; one "
                    "registry manifest per topology point")
    ap.add_argument("--device_counts", default="1,2,4",
                    help="comma-separated single-process points "
                         "(default 1,2,4; each must divide "
                         f"{W} workers)")
    ap.add_argument("--rounds", type=int, default=ROUNDS_DEFAULT)
    ap.add_argument("--runs_dir", default="runs")
    ap.add_argument("--mesh_shapes", default="",
                    help="comma-separated 2D mesh layouts to append "
                         "as extra points (e.g. 8x1,4x2,2x4,1x8); "
                         "each CxM point runs on C*M virtual devices "
                         f"and C must divide {W} workers")
    ap.add_argument("--sketch_dtypes", default="",
                    help="comma-separated uplink wire dtypes "
                         "(bf16,int8,fp8) to append as extra points "
                         "on the largest requested device count; "
                         "each point's perf-gate key gets a q<dtype> "
                         "suffix")
    ap.add_argument("--overlap_depths", default="",
                    help="comma-separated round-pipeline chunk "
                         "depths (e.g. 1,2,4) to append as extra "
                         "int8-wire points on the largest requested "
                         "device count; each depth>1 point's "
                         "perf-gate key gets an o<N> suffix")
    ap.add_argument("--resize", default="",
                    help="elastic-resume pair 'CxM:C2xM2': run the "
                         "workload on the first mesh, checkpoint it, "
                         "then resume the SAME run on the second "
                         "mesh/device count — the resume-cost point "
                         "(its manifest carries resumed_from + "
                         "topology_segments, so the perf gate "
                         "refuses to pin the merged ledger)")
    ap.add_argument("--multihost", action="store_true",
                    help="append a 2-process point (2 devices per "
                         "process) and merge its ledger shards")
    ap.add_argument("--timeout", type=float, default=900.0,
                    help="per-point subprocess timeout, seconds")
    # worker-mode flags (spawned internally)
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--devices", type=int, default=1,
                    help=argparse.SUPPRESS)
    ap.add_argument("--mesh", default="", help=argparse.SUPPRESS)
    ap.add_argument("--sketch_dtype", default="f32",
                    help=argparse.SUPPRESS)
    ap.add_argument("--overlap_depth", type=int, default=1,
                    help=argparse.SUPPRESS)
    ap.add_argument("--ledger", default="", help=argparse.SUPPRESS)
    ap.add_argument("--ckpt_save", default="", help=argparse.SUPPRESS)
    ap.add_argument("--ckpt_resume", default="",
                    help=argparse.SUPPRESS)
    ap.add_argument("--ref_clients_per_s", type=float, default=0.0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--ref_devices", type=int, default=1,
                    help=argparse.SUPPRESS)
    ap.add_argument("--process_id", type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--num_processes", type=int, default=1,
                    help=argparse.SUPPRESS)
    ap.add_argument("--coordinator", default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.worker:
        return worker(args)

    counts = sorted({int(x) for x in args.device_counts.split(",")})
    for n in counts:
        if W % n:
            ap.error(f"device count {n} does not divide {W} workers")
    shapes = [s.strip() for s in args.mesh_shapes.split(",")
              if s.strip()]
    for s in shapes:
        c, m = (int(p) for p in s.lower().split("x"))
        if W % c:
            ap.error(f"mesh shape {s}: clients axis {c} does not "
                     f"divide {W} workers")
    dtypes = [s.strip() for s in args.sketch_dtypes.split(",")
              if s.strip()]
    for dt in dtypes:
        if dt not in ("f32", "bf16", "int8", "fp8"):
            ap.error(f"unknown sketch dtype {dt}")
    depths = [int(s) for s in args.overlap_depths.split(",")
              if s.strip()]
    for n2 in depths:
        if n2 < 1:
            ap.error(f"overlap depth {n2} must be >= 1")
    resize = []
    if args.resize:
        halves = args.resize.lower().split(":")
        try:
            resize = [tuple(int(p) for p in h.split("x"))
                      for h in halves]
            ok = len(resize) == 2 and all(len(t) == 2 for t in resize)
        except ValueError:
            ok = False
        if not ok:
            ap.error(f"--resize wants 'CxM:C2xM2', got "
                     f"{args.resize!r}")
        for c, m in resize:
            if W % c:
                ap.error(f"resize mesh {c}x{m}: clients axis {c} "
                         f"does not divide {W} workers")
    stamp = int(time.time())
    points, ref = [], None

    def show(label, point):
        print(f"{label}: {point['clients_per_s']} clients/s, "
              f"eff {point['parallel_efficiency']:.2f}, "
              f"collective {point['collective_fraction'] * 100:.1f}%, "
              f"skew max {point['max_skew_s']} s", flush=True)

    for n in counts:
        point, _ = _run_point(n, args, ref, stamp)
        if ref is None:
            ref = (point["clients_per_s"], n)
        points.append(point)
        show(f"d{n}p1", point)

    for s in shapes:
        c, m = (int(p) for p in s.lower().split("x"))
        point, _ = _run_point(c * m, args, ref, stamp,
                              extra_cmd=["--mesh", s.lower()],
                              tag=f"m{c}x{m}")
        if ref is None:
            ref = (point["clients_per_s"], c * m)
        points.append(point)
        show(f"d{c * m}p1 mesh {c}x{m}", point)

    for dt in dtypes:
        n = max(counts) if counts else 1
        point, _ = _run_point(n, args, ref, stamp,
                              extra_cmd=["--sketch_dtype", dt],
                              tag=f"q{dt}")
        if ref is None:
            ref = (point["clients_per_s"], n)
        points.append(point)
        show(f"d{n}p1 q{dt} "
             f"({point['upload_wire_bytes_per_client']:.0f} B/client)",
             point)

    for n2 in depths:
        n = max(counts) if counts else 1
        point, _ = _run_point(
            n, args, ref, stamp,
            extra_cmd=["--overlap_depth", str(n2),
                       "--sketch_dtype", "int8"],
            tag=f"o{n2}" if n2 > 1 else "o1")
        if ref is None:
            ref = (point["clients_per_s"], n)
        points.append(point)
        show(f"d{n}p1 qint8 o{n2} (overlapped "
             f"{point['overlapped_fraction'] * 100:.1f}% of "
             "collective)", point)

    if resize:
        (c1, m1), (c2, m2) = resize
        ckpt = os.path.join(args.runs_dir, "scaling",
                            f"resize_{stamp}.npz")
        point, _ = _run_point(
            c1 * m1, args, ref, stamp,
            extra_cmd=["--mesh", f"{c1}x{m1}", "--ckpt_save", ckpt],
            tag=f"m{c1}x{m1}rz0")
        if ref is None:
            ref = (point["clients_per_s"], c1 * m1)
        points.append(point)
        show(f"d{c1 * m1}p1 mesh {c1}x{m1} (pre-resize)", point)
        point, _ = _run_point(
            c2 * m2, args, ref, stamp,
            extra_cmd=["--mesh", f"{c2}x{m2}", "--ckpt_resume", ckpt],
            tag=f"m{c2}x{m2}rz1")
        points.append(point)
        show(f"d{c2 * m2}p1 mesh {c2}x{m2} (resumed, load "
             f"{point.get('resume_load_s', 0.0)} s)", point)

    if args.multihost:
        point, ledger = _run_point(4, args, ref, stamp, nproc=2)
        if point is None:
            print("d4p2: SKIP (CPU backend lacks multiprocess "
                  "computations)", flush=True)
        else:
            points.append(point)
            print(f"d4p2: {point['clients_per_s']} clients/s, "
                  f"eff {point['parallel_efficiency']:.2f}, "
                  f"collective "
                  f"{point['collective_fraction'] * 100:.1f}%, "
                  f"skew max {point['max_skew_s']} s", flush=True)
            merge = os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "ledger_merge.py")
            subprocess.run([sys.executable, merge, ledger],
                           check=True)

    print(f"{len(points)} scaling point(s) registered under "
          f"{args.runs_dir} — render the curve with:\n"
          f"  python scripts/telemetry_report.py --runs_dir "
          f"{args.runs_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
