"""Hardware self-test: exercises the TPU-only code paths the pytest
suite cannot (it runs on a virtual CPU mesh with Pallas in interpret
mode). Run on a machine with a TPU attached:

    python scripts/tpu_selftest.py

Prints one PASS/FAIL line per check and exits nonzero on any failure.
"""

import os
import sys
import time

# runnable as `python scripts/tpu_selftest.py` without installing
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

FAILED = []


def check(name, fn):
    try:
        t0 = time.perf_counter()
        detail = fn()
        dt = time.perf_counter() - t0
        print(f"PASS  {name}  ({dt:.1f}s{'; ' + detail if detail else ''})")
    except Exception as e:  # noqa: BLE001 — report and continue
        FAILED.append(name)
        print(f"FAIL  {name}: {type(e).__name__}: {e}")


def pallas_parity():
    """Compiled Pallas kernels vs the XLA path at flagship geometry."""
    from commefficient_tpu.ops.sketch import CountSketch

    d, c, r = 6_600_000, 524288, 5
    xla = CountSketch(d=d, c=c, r=r, seed=7, backend="xla")
    pal = CountSketch(d=d, c=c, r=r, seed=7, backend="pallas")
    assert pal._resolve_backend() == "pallas", "not on TPU?"
    v = jnp.asarray(np.random.RandomState(0).randn(d).astype(np.float32))
    tx = jax.jit(xla.sketch)(v)
    tp = jax.jit(pal.sketch)(v)
    assert jnp.allclose(tx, tp, rtol=1e-6, atol=1e-4), "tables differ"
    ex = np.asarray(jax.jit(xla.estimates)(tx))
    ep = np.asarray(jax.jit(pal.estimates)(tx))
    assert (ex == ep).all(), "recovery not bit-exact"
    return "hash-identical tables, bit-exact recovery"


def bf16_round_trains():
    """Full-size bf16 ResNet9 sketch round executes and is finite."""
    from commefficient_tpu.config import Config
    from commefficient_tpu.core.rounds import (ClientStates,
                                               build_client_round,
                                               build_server_round)
    from commefficient_tpu.core.server import ServerState
    from commefficient_tpu.models import get_model
    from commefficient_tpu.ops.vec import flatten_params
    from commefficient_tpu.train.cv_train import make_compute_loss

    W, B = 8, 8
    cfg = Config(mode="sketch", error_type="virtual",
                 local_momentum=0.0, virtual_momentum=0.9,
                 weight_decay=5e-4, num_workers=W, local_batch_size=B,
                 k=50000, num_rows=5, num_cols=524288,
                 dataset_name="CIFAR10", seed=21, approx_topk=True)
    module = get_model("ResNet9")(num_classes=10, dtype=jnp.bfloat16)
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 32, 32, 3)))["params"]
    flat, unravel = flatten_params(params)
    cfg.grad_size = int(flat.size)
    loss = make_compute_loss(module)
    cr = jax.jit(build_client_round(
        cfg, lambda p, b: loss(unravel(p), b, cfg), B))
    sr = jax.jit(build_server_round(cfg))
    rng = np.random.RandomState(0)
    batch = {"x": jnp.asarray(rng.randn(W, B, 32, 32, 3)
                              .astype(np.float32)),
             "y": jnp.asarray(rng.randint(0, 10, (W, B))
                              .astype(np.int32)),
             "mask": jnp.ones((W, B), jnp.float32)}
    res = cr(flat, ClientStates.init(cfg, 100, flat), batch,
             jnp.arange(W, dtype=jnp.int32), jax.random.PRNGKey(0),
             1.0)
    ps2, _, _, upd, sup = sr(flat, ServerState.init(cfg),
                             res.aggregated, jnp.float32(0.1))
    assert bool(jnp.isfinite(ps2).all())
    nnz = int((np.asarray(upd) != 0).sum()) if upd is not None \
        else int((np.asarray(sup[1]) != 0).sum())
    assert 0 < nnz <= cfg.k
    return f"update nnz {nnz}"


def probe_smoke():
    """--probe_full program variant on a sketch round: the in-compile
    diagnostics come back clean and the TRUE recovery error against
    the dense gradient is finite and < 1 (heavy-hitter gradient, so
    top-k recovery must capture most of the mass)."""
    from commefficient_tpu.config import Config
    from commefficient_tpu.core.rounds import (ClientStates,
                                               build_client_round,
                                               build_server_round)
    from commefficient_tpu.core.server import ServerState

    W, B, d = 8, 4, 1 << 18
    cfg = Config(mode="sketch", error_type="virtual",
                 local_momentum=0.0, virtual_momentum=0.9,
                 num_workers=W, local_batch_size=B,
                 k=5000, num_rows=5, num_cols=65536, seed=21)
    cfg.grad_size = d

    def lin_loss(p, b):
        # grad == the client's c vector exactly (masked batch mean of
        # identical rows) — a known ground truth for the probes
        n = jnp.maximum(jnp.sum(b["mask"]), 1.0)
        loss = jnp.sum((b["c"] @ p) * b["mask"]) / n
        return loss, (loss * 0.0,)

    cr = jax.jit(build_client_round(cfg, lin_loss, B, probes=True,
                                    probe_recovery=True))
    sr = jax.jit(build_server_round(cfg, probes=True))
    rng = np.random.RandomState(0)
    # heavy-tailed coordinates: the top-k floor of the recovery error
    # stays well below 1
    c = rng.randn(W, 1, d).astype(np.float32)
    c[:, :, :2000] *= 50.0
    batch = {"c": jnp.asarray(np.broadcast_to(c, (W, B, d))),
             "mask": jnp.ones((W, B), jnp.float32)}
    flat = jnp.zeros((d,), jnp.float32)
    res = cr(flat, ClientStates.init(cfg, 100, flat), batch,
             jnp.arange(W, dtype=jnp.int32), jax.random.PRNGKey(0),
             1.0)
    pr = {k: float(v) for k, v in res.probes.items()}
    out = sr(flat, ServerState.init(cfg), res.aggregated,
             jnp.float32(0.1))
    pr.update({k: float(v) for k, v in out[-1].items()})
    assert pr["agg_nan"] == 0 and pr["agg_inf"] == 0, pr
    rec = pr["recovery_error"]
    assert np.isfinite(rec) and 0.0 <= rec < 1.0, pr
    for key in ("update_norm", "residual_norm", "momentum_norm",
                "mass_coverage"):
        assert np.isfinite(pr[key]), pr
    return f"recovery error {rec:.3f}"


def quant_smoke():
    """Quantized uplink path on the REAL backend: the fused Pallas
    emit+quantize kernel must agree bit-for-bit with the unfused
    quantize_local(sketch(.)) path on-device for every wire dtype,
    and a quantized sketch round's TRUE recovery error must stay
    inside the alarm band of the f32 reference round (per-row scales
    bound the quantization penalty; server momentum/EF stays f32) —
    while moving ~4x fewer uplink bytes."""
    from commefficient_tpu import accounting
    from commefficient_tpu.config import Config
    from commefficient_tpu.core.rounds import (ClientStates,
                                               build_client_round,
                                               build_server_round)
    from commefficient_tpu.core.server import ServerState
    from commefficient_tpu.ops.quant import quantize_local
    from commefficient_tpu.ops.sketch import CountSketch

    d = 1 << 16
    cs = CountSketch(d=d, c=4096, r=3, seed=7)
    v = jnp.asarray(np.random.RandomState(0).randn(d)
                    .astype(np.float32))
    for wire in ("bf16", "int8", "fp8"):
        qf, _ = jax.jit(lambda x, w=wire: cs.sketch_quantized(x, w))(v)
        qu, _ = jax.jit(
            lambda x, w=wire: quantize_local(cs.sketch(x), w))(v)
        assert np.asarray(qf).tobytes() == np.asarray(qu).tobytes(), \
            f"{wire}: fused kernel != unfused quantize"

    W, B = 8, 4

    def lin_loss(p, b):
        n = jnp.maximum(jnp.sum(b["mask"]), 1.0)
        loss = jnp.sum((b["c"] @ p) * b["mask"]) / n
        return loss, (loss * 0.0,)

    rng = np.random.RandomState(0)
    cvec = rng.randn(W, 1, d).astype(np.float32)
    cvec[:, :, :500] *= 50.0  # heavy hitters: recovery floor << 1
    batch = {"c": jnp.asarray(np.broadcast_to(cvec, (W, B, d))),
             "mask": jnp.ones((W, B), jnp.float32)}
    flat = jnp.zeros((d,), jnp.float32)
    errs = {}
    for wire in ("f32", "int8", "fp8"):
        cfg = Config(mode="sketch", error_type="virtual",
                     local_momentum=0.0, virtual_momentum=0.9,
                     num_workers=W, local_batch_size=B, k=500,
                     num_rows=5, num_cols=16384, seed=21,
                     sketch_dtype=wire)
        cfg.grad_size = d
        cr = jax.jit(build_client_round(cfg, lin_loss, B, probes=True,
                                        probe_recovery=True))
        sr = jax.jit(build_server_round(cfg, probes=True))
        res = cr(flat, ClientStates.init(cfg, 100, flat), batch,
                 jnp.arange(W, dtype=jnp.int32),
                 jax.random.PRNGKey(0), 1.0)
        out = sr(flat, ServerState.init(cfg), res.aggregated,
                 jnp.float32(0.1))
        assert bool(jnp.isfinite(out[0]).all()), wire
        pr = {k: float(x) for k, x in res.probes.items()}
        pr.update({k: float(x) for k, x in out[-1].items()})
        assert pr["agg_nan"] == 0 and pr["agg_inf"] == 0, (wire, pr)
        errs[wire] = pr["recovery_error"]
    band = max(2.0 * errs["f32"], errs["f32"] + 0.05)
    assert errs["int8"] <= band, errs
    assert errs["fp8"] <= band, errs
    ratio = (accounting.sketch_wire_bytes(5, 16384, "f32")
             / accounting.sketch_wire_bytes(5, 16384, "int8"))
    return (f"fused==unfused bitwise; recovery err f32 "
            f"{errs['f32']:.3f} int8 {errs['int8']:.3f} fp8 "
            f"{errs['fp8']:.3f}; uplink {ratio:.2f}x smaller at int8")


def overlap_smoke():
    """Latency-hiding round pipeline (--overlap_depth) on the REAL
    backend: a depth-2 chunked int8 round must be BIT-IDENTICAL to
    the depth-1 serial round (per-row scales make every chunk the
    exact row slice of the whole-table algebra — the pipeline
    reorders the schedule, never the math), and a traced pipelined
    round must land an ``overlapped_s`` bucket in its device-time
    attribution for the observatory to read."""
    import shutil
    import tempfile

    from commefficient_tpu.config import Config
    from commefficient_tpu.core.rounds import (ClientStates,
                                               build_client_round)
    from commefficient_tpu.parallel.mesh import client_sharding, make_mesh
    from commefficient_tpu.telemetry import trace
    from commefficient_tpu.telemetry.profiler import trace_window

    W, B, d = 8, 4, 1 << 16

    def lin_loss(p, b):
        n = jnp.maximum(jnp.sum(b["mask"]), 1.0)
        loss = jnp.sum((b["c"] @ p) * b["mask"]) / n
        return loss, (loss * 0.0,)

    rng = np.random.RandomState(0)
    batch = {"c": jnp.asarray(rng.randn(W, B, d).astype(np.float32)),
             "mask": jnp.ones((W, B), jnp.float32)}
    flat = jnp.zeros((d,), jnp.float32)
    mesh = make_mesh()
    sharded = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, client_sharding(mesh)), batch)
    aggs, rounds = {}, {}
    for depth in (1, 2):
        cfg = Config(mode="sketch", error_type="virtual",
                     local_momentum=0.0, virtual_momentum=0.9,
                     num_workers=W, local_batch_size=B, k=500,
                     num_rows=4, num_cols=16384, seed=21,
                     sketch_dtype="int8", overlap_depth=depth)
        cfg.grad_size = d
        cr = jax.jit(build_client_round(cfg, lin_loss, B, mesh=mesh))
        res = cr(flat, ClientStates.init(cfg, W, flat), sharded,
                 jnp.arange(W, dtype=jnp.int32),
                 jax.random.PRNGKey(0), 1.0)
        aggs[depth] = np.asarray(res.aggregated)
        rounds[depth] = (cr, res.client_states)
    assert aggs[1].tobytes() == aggs[2].tobytes(), \
        "depth-2 pipelined round != depth-1 serial round"

    # a traced pipelined round must carry the overlapped_s bucket
    logdir = tempfile.mkdtemp(prefix="overlap_smoke_")
    try:
        cr, cs = rounds[2]
        with trace_window(logdir):
            trace.begin_round_marker(0)
            cr(flat, cs, sharded, jnp.arange(W, dtype=jnp.int32),
               jax.random.PRNGKey(1), 1.0
               ).aggregated.block_until_ready()
        buckets = trace.attribute_logdir(logdir)
        assert buckets, "no rounds attributed"
        b0 = buckets[sorted(buckets)[0]]
        ovl = b0.get("overlapped_s")
        assert ovl is not None and ovl >= 0.0, b0
        assert ovl <= b0["collective_s"] + 1e-9, b0
    finally:
        shutil.rmtree(logdir, ignore_errors=True)
    return (f"depth-2 bitwise == depth-1; overlapped "
            f"{ovl * 1e3:.2f} ms of "
            f"{b0['collective_s'] * 1e3:.2f} ms collective")


def async_smoke():
    """Buffered asynchronous rounds (asyncfed) on the REAL backend:
    the degenerate configuration — buffer size == cohort, staleness
    weight 0, punctual arrivals — must be BIT-IDENTICAL to the
    synchronous barrier round (the async driver adds bookkeeping,
    never math), and a churny arrival schedule must land its
    staleness histogram in the telemetry ledger for the observatory
    to read."""
    import json
    import shutil
    import tempfile

    from commefficient_tpu.config import Config
    from commefficient_tpu.data.chaos import ArrivalSchedule
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)

    W, B, d = 8, 2, 1 << 10

    def loss(params, batch, cfg):
        pred = batch["x"] @ params["w"]
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
        return l, (l * 0.0 + 1.0,)

    def run(async_k, alpha, sched=None, ledger=""):
        cfg = Config(mode="sketch", error_type="virtual",
                     local_momentum=0.0, virtual_momentum=0.9, k=32,
                     num_rows=3, num_cols=256, num_workers=W,
                     local_batch_size=B, num_clients=64, seed=3,
                     async_buffer_size=async_k,
                     async_staleness_weight=alpha, ledger=ledger)
        model = FedModel(None, {"w": jnp.zeros((d,), jnp.float32)},
                         loss, cfg, padded_batch_size=B)
        opt = FedOptimizer([{"lr": 0.25}], cfg, model=model)
        if sched is not None:
            model.attach_arrival_process(sched)
        rng = np.random.RandomState(3)
        for _ in range(6):
            batch = {"client_ids": rng.choice(64, W, replace=False)
                     .astype(np.int32),
                     "x": jnp.asarray(rng.randn(W, B, d), jnp.float32),
                     "y": jnp.asarray(rng.randn(W, B), jnp.float32),
                     "mask": jnp.ones((W, B), jnp.float32)}
            model(batch)
            opt.step()
        ps = np.asarray(model.ps_weights)
        model.finalize()
        return ps

    sync = run(0, 0.0)
    deg = run(W, 0.0)  # K == cohort, punctual: the barrier in disguise
    assert np.array_equal(sync, deg), "degenerate async != sync round"

    tmp = tempfile.mkdtemp(prefix="async_smoke_")
    try:
        led = os.path.join(tmp, "ledger.jsonl")
        run(4, 0.5, sched=ArrivalSchedule("churny", seed=3),
            ledger=led)
        hist = None
        with open(led) as f:
            for line in f:
                rec = json.loads(line)
                pr = rec.get("probes") or {}
                if "async_staleness_hist" in pr:
                    hist = pr["async_staleness_hist"]
        assert hist is not None, "no staleness histogram in ledger"
        assert sum(hist) > 0, hist
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return ("degenerate buffered round bitwise == sync; churny "
            f"staleness hist {hist}")


def autopilot_smoke():
    """Adaptive compression autopilot on the REAL backend: from an f32
    launch the probe-driven controller must walk to a cheaper wire
    dtype while the recovery error holds the band on every observed
    round, with the re-jit cache compiling no more round variants than
    lattice points actually visited (warm-ahead never compiles an
    unvisited point) and the recorded trajectory replaying
    bit-exactly."""
    from commefficient_tpu.autopilot import parse_band, replay_record
    from commefficient_tpu.config import Config
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)

    W, B, d = 4, 2, 512

    def loss(params, batch, cfg):
        pred = batch["x"] @ params["w"]
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
        return l, (l * 0.0 + 1.0,)

    cfg = Config(mode="sketch", error_type="virtual",
                 local_momentum=0.0, virtual_momentum=0.9,
                 num_workers=W, local_batch_size=B, seed=5,
                 num_clients=16, k=64, num_rows=5, num_cols=2048,
                 sketch_dtype="f32", probe_every=1, autopilot="on",
                 autopilot_band="0.05:0.6", autopilot_cooldown=1)
    model = FedModel(None, {"w": jnp.zeros((d,), jnp.float32)},
                     loss, cfg, padded_batch_size=B)
    opt = FedOptimizer([{"lr": 0.25}], cfg, model=model)
    scale = (np.arange(1, d + 1) ** -1.5).astype(np.float32)
    rng = np.random.RandomState(5)
    for _ in range(8):
        model({"client_ids": rng.choice(16, W, replace=False)
               .astype(np.int32),
               "x": jnp.asarray(rng.randn(W, B, d).astype(np.float32)
                                * scale),
               "y": jnp.asarray(rng.randn(W, B), jnp.float32),
               "mask": jnp.ones((W, B), jnp.float32)})
        opt.step()
    rec = model.autopilot_record()
    counters = model._variants.counters()
    model.finalize()

    lo, hi = parse_band(cfg.autopilot_band)
    observed = [t for t in rec["trajectory"]
                if t["recovery_error"] is not None]
    assert observed, "no recovery observations reached the controller"
    assert all(t["recovery_error"] <= hi for t in observed), observed
    assert not any(t["action"] == "panic"
                   for t in rec["trajectory"]), rec["trajectory"]
    assert rec["final_wire_bytes"] * 2 <= rec["initial_wire_bytes"], rec
    visited = {t["key"] for t in rec["trajectory"]}
    visited.add(rec["initial"])
    assert counters["misses"] <= len(visited), (counters, visited)
    assert replay_record(rec) == [t["key"] for t in rec["trajectory"]]
    return (f"{rec['initial'].split('-', 1)[0]} -> {rec['final']}, "
            f"uplink {rec['initial_wire_bytes'] / rec['final_wire_bytes']:.1f}x "
            f"smaller, {counters['misses']} compiles / "
            f"{len(visited)} points visited")


def audit_smoke():
    """Static audit on the REAL backend: zero unwaived lint hits, and
    the sketch fused round compiled for this topology is donation-
    covered and host-transfer-free, with the table psum's wire bytes
    matching the ledger's 4·r·c per-client uplink when the mesh
    actually spans devices. (The fingerprint-vs-baseline diff is a
    CPU-mesh-only check — compiled text differs per platform — so
    it stays in tier-1, not here.)"""
    from commefficient_tpu.analysis.lint import run_lint, unwaived
    from commefficient_tpu.analysis.program import (ProgramSpec,
                                                    audit_client_program)
    from commefficient_tpu.parallel.mesh import make_mesh

    hits = unwaived(run_lint())
    assert not hits, f"unwaived lint violations: {hits[:5]}"
    spec = ProgramSpec("sketch/fused", "sketch", "fused",
                       dict(error_type="virtual",
                            virtual_momentum=0.9))
    entry = audit_client_program(spec, mesh=make_mesh(jax.devices()))
    assert not entry["failures"], entry["failures"]
    counts = entry["collectives"]["counts"]
    # the fused shard_map branch engages when the W=8 fan-out divides
    # the mesh; odd device counts fall back to single-device (no psum)
    if jax.device_count() > 1 and 8 % jax.device_count() == 0:
        assert counts.get("all-reduce"), entry["collectives"]
    return (f"lint clean; sketch/fused collectives {counts or '{}'} "
            f"fp {entry['fingerprint'][:12]}")


def flowlint_smoke():
    """The flowlint whole-program tier on the deployed tree: zero
    unwaived findings from the call-graph checkers (trace-purity,
    prng-keys, wire-dtype-crossing, lock-confinement) and the engine
    staying inside its 10 s wall-time budget — a daemon image ships
    with the same static guarantees CI pinned."""
    import time as _time

    from commefficient_tpu.analysis.flow import build_program
    from commefficient_tpu.analysis.lint import (run_all, unwaived)

    t0 = _time.monotonic()
    program = build_program(None)
    hits = unwaived(run_all(program=program))
    elapsed = _time.monotonic() - t0
    assert not hits, f"unwaived flowlint findings: {hits[:5]}"
    assert elapsed < 10.0, f"engine took {elapsed:.1f}s (budget 10s)"
    return (f"flow tier clean; {len(program.jit_roots)} jit roots, "
            f"{len(program.thread_roots)} thread roots, "
            f"{len(program.traced)} traced fns in {elapsed:.1f}s")


def flash_attention_parity():
    """attn_impl="flash" (Pallas flash-attention kernel) vs the XLA
    attention lowering on the same GPT-2 block — forward and gradient
    agreement at bf16 tolerance. T=256 takes block 256; T=640 takes
    the divisor-selection path (640 = 5·128: block must DIVIDE T, not
    just bound it — the round-4 review crash case)."""
    import dataclasses

    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads

    details = []
    for T in (256, 640):
        base = GPT2Config(vocab_size=512, n_positions=1024, n_embd=256,
                          n_layer=2, n_head=4, dtype=jnp.bfloat16)
        ids = jnp.asarray(np.random.RandomState(0).randint(
            0, 512, (2, 2, T)), jnp.int32)
        mc = jnp.full((2, 2), T - 1, jnp.int32)

        outs = {}
        for impl in ("xla", "flash"):
            cfg = dataclasses.replace(base, attn_impl=impl)
            m = GPT2DoubleHeads(cfg)
            p = m.init(jax.random.PRNGKey(0), ids, mc, ids)["params"]

            def loss(pp, m=m, ids=ids, mc=mc):
                lm, mcl = m.apply({"params": pp}, ids, mc, ids)
                return jnp.sum(lm.astype(jnp.float32) ** 2) * 1e-6 + \
                    jnp.sum(mcl.astype(jnp.float32) ** 2) * 1e-3

            l, g = jax.jit(jax.value_and_grad(loss))(p)
            gflat = jnp.concatenate([jnp.ravel(x) for x in
                                     jax.tree_util.tree_leaves(g)])
            outs[impl] = (float(l), np.asarray(gflat, np.float32))
        lx, gx = outs["xla"]
        lf, gf = outs["flash"]
        assert abs(lx - lf) / max(abs(lx), 1e-6) < 2e-2, (T, lx, lf)
        denom = np.maximum(np.abs(gx), 1e-3)
        rel = np.abs(gx - gf) / denom
        assert np.median(rel) < 2e-2, (T, float(np.median(rel)))
        details.append(f"T={T} grad rel {np.median(rel):.1e}")
    return "; ".join(details)


def trace_smoke():
    """Device-time attribution round-trip on the REAL backend: a
    trace_window around a few marked rounds of device work must
    produce round windows whose buckets are internally consistent
    (disjoint buckets summing to the window) with nonzero device busy
    time — TPU xplanes name their lanes differently from the CPU
    backend the pytest fixture covers, so the lane detection is what
    this check actually exercises."""
    import shutil
    import tempfile

    from commefficient_tpu.telemetry import trace
    from commefficient_tpu.telemetry.profiler import trace_window

    logdir = tempfile.mkdtemp(prefix="trace_smoke_")
    try:
        x = jnp.asarray(np.random.RandomState(0)
                        .randn(2048, 2048).astype(np.float32))
        f = jax.jit(lambda a: a @ a.T + 1.0)
        f(x).block_until_ready()  # compile outside the window
        with trace_window(logdir):
            for r in range(3):
                trace.begin_round_marker(r)
                f(x).block_until_ready()
        buckets = trace.attribute_logdir(logdir)
        assert len(buckets) == 3, sorted(buckets)
        busy = sum(b["busy_s"] for b in buckets.values())
        assert busy > 0, buckets
        for r, b in buckets.items():
            parts = (b["compute_s"] + b["collective_s"]
                     + b["transfer_s"] + b["host_gap_s"])
            assert abs(parts - b["window_s"]) <= 1e-5, (r, b)
        return (f"3 rounds attributed, busy {busy * 1e3:.1f} ms, "
                f"compute {sum(b['compute_s'] for b in buckets.values()) * 1e3:.1f} ms")
    finally:
        shutil.rmtree(logdir, ignore_errors=True)


def scaling_smoke():
    """Two-point CPU scaling sweep straight through the run registry:
    scripts/scaling_bench.py must register one manifest per topology
    point (distinct (device_count, process_count) keys, shared config
    hash) with a ``scaling`` block the report can render as a curve.
    Pinned to the virtual CPU mesh on purpose — the registry/manifest
    plumbing is backend-independent, and the real-TPU throughput
    points come from running scaling_bench against the pod itself."""
    import shutil
    import subprocess
    import tempfile

    from commefficient_tpu.telemetry import registry

    runs_dir = tempfile.mkdtemp(prefix="scaling_smoke_")
    try:
        script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "scaling_bench.py")
        out = subprocess.run(
            [sys.executable, script, "--device_counts", "1,2",
             "--rounds", "3", "--runs_dir", runs_dir],
            capture_output=True, text=True, timeout=560)
        assert out.returncode == 0, out.stdout + out.stderr
        manifests = registry.list_manifests(runs_dir)
        topos = sorted(registry.run_topology(m) for _, m in manifests)
        assert topos == [(1, 1), (2, 1)], topos
        hashes = {m.get("config_hash") for _, m in manifests}
        assert len(hashes) == 1, hashes
        for _, m in manifests:
            sc = m.get("scaling")
            assert sc and sc["clients_per_s"] > 0, m
            assert 0.0 < sc["parallel_efficiency"], m
        eff2 = [m["scaling"]["parallel_efficiency"]
                for _, m in manifests
                if registry.run_topology(m) == (2, 1)][0]
        return f"2 points registered, d2p1 efficiency {eff2:.2f}"
    finally:
        shutil.rmtree(runs_dir, ignore_errors=True)


def mesh2d_smoke():
    """2D clients x model mesh on the REAL backend: the pod-scale
    sketch round (partial tables reduce-scattered over ``model``,
    column-sharded server momentum/EF, distributed top-k select) must
    match the 1-D oracle round on this hardware, with per-device
    server shards at 1/M of the table. The mesh shape adapts to the
    attached topology (model axis 2 whenever the device count is
    even)."""
    from commefficient_tpu.config import Config
    from commefficient_tpu.core.rounds import (ClientStates,
                                               build_client_round,
                                               build_server_round)
    from commefficient_tpu.core.server import ServerState
    from commefficient_tpu.parallel.mesh import (client_sharding,
                                                 make_mesh2d,
                                                 model_axis_size,
                                                 server_state_sharding)

    n = jax.device_count()
    m = 2 if n % 2 == 0 else 1
    c = n // m
    W, B, d = 2 * c, 2, 1 << 12
    cfg = Config(mode="sketch", error_type="virtual",
                 local_momentum=0.0, virtual_momentum=0.9,
                 weight_decay=5e-4, num_workers=W, local_batch_size=B,
                 k=64, num_rows=3, num_cols=512, seed=21,
                 mesh=f"{c}x{m}")
    cfg.grad_size = d
    cfg.validate_runtime()

    def lin_loss(p, b):
        nm = jnp.maximum(jnp.sum(b["mask"]), 1.0)
        loss = jnp.sum((b["c"] @ p) * b["mask"]) / nm
        return loss, (loss * 0.0,)

    rng = np.random.RandomState(0)
    batch = {"c": jnp.asarray(rng.randn(W, B, d).astype(np.float32)),
             "mask": jnp.ones((W, B), jnp.float32)}
    flat = jnp.zeros((d,), jnp.float32).at[0].set(0.5)

    def run(mesh):
        two_d = mesh is not None and model_axis_size(mesh) > 1
        cr = jax.jit(build_client_round(cfg, lin_loss, B, mesh=mesh))
        sr = jax.jit(build_server_round(
            cfg, mesh=mesh if two_d else None))
        ss = ServerState.init(
            cfg, sharding=(server_state_sharding(mesh,
                                                 cfg.transmit_shape)
                           if two_d else None))
        ps, cs = flat, ClientStates.init(cfg, W, flat)
        b = batch
        if mesh is not None:
            b = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, client_sharding(mesh)), b)
        for r in range(2):
            res = cr(ps, cs, b, jnp.arange(W, dtype=jnp.int32),
                     jax.random.PRNGKey(r), 1.0)
            cs = res.client_states
            ps, ss, _, _, _ = sr(ps, ss, res.aggregated,
                                 jnp.float32(0.1))
        return np.asarray(ps), np.asarray(ss.Vvelocity), ss

    ps2, vel2, ss2 = run(make_mesh2d(c, m))
    ps1, vel1, _ = run(None)
    scale = max(float(np.abs(ps1).max()), 1e-6)
    err = float(np.abs(ps2 - ps1).max()) / scale
    assert err < 1e-4, err
    np.testing.assert_allclose(vel2, vel1, rtol=0, atol=1e-4)
    if m > 1:
        shapes = {tuple(s.data.shape)
                  for s in ss2.Verror.addressable_shards}
        assert shapes == {(cfg.num_rows, cfg.num_cols // m)}, shapes
    return f"mesh {c}x{m}: params rel err {err:.1e}"


def elastic_smoke():
    """Topology-changing restore on the REAL backend: checkpoint a
    sketch run on a 2x1 clients x model mesh, restore it onto a 1x2
    mesh (same chips, transposed layout), and require the restored
    state bit-identical — asserted by re-saving from the resized model
    and comparing the two archives array for array. The placement
    moved; the values must not."""
    import json
    import tempfile

    from commefficient_tpu.config import Config
    from commefficient_tpu.runtime import FedModel, FedOptimizer
    from commefficient_tpu.runtime.checkpoint import (load_checkpoint,
                                                      save_checkpoint)

    if jax.device_count() < 2:
        return "skipped (needs >= 2 devices)"

    W, B, D = 4, 2, 256

    def loss(p, batch, _cfg):
        pred = batch["x"] @ p["w"]
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        return jnp.sum((pred - batch["y"]) ** 2
                       * batch["mask"]) / n, ()

    def build(mesh):
        cfg = Config(mode="sketch", error_type="virtual",
                     local_momentum=0.0, virtual_momentum=0.9,
                     num_workers=W, local_batch_size=B,
                     num_clients=2 * W, dataset_name="CIFAR10",
                     seed=3, k=16, num_rows=3, num_cols=128,
                     mesh=mesh)
        model = FedModel(None, {"w": jnp.zeros((D,), jnp.float32)},
                         loss, cfg, padded_batch_size=B)
        opt = FedOptimizer([{"lr": 0.2}], cfg, model=model)
        return model, opt

    def mk(r):
        rng = np.random.RandomState(100 + r)
        return {"x": rng.randn(W, B, D).astype(np.float32),
                "y": rng.randn(W, B).astype(np.float32),
                "mask": np.ones((W, B), np.float32),
                "client_ids": np.arange(r, r + W,
                                        dtype=np.int32) % (2 * W)}

    tmp = tempfile.mkdtemp(prefix="elastic_smoke_")
    ck_a = os.path.join(tmp, "a.npz")
    ck_b = os.path.join(tmp, "b.npz")
    model, opt = build("2x1")
    for r in range(3):
        model(mk(r))
        opt.step()
    save_checkpoint(ck_a, model, opt)
    model.finalize()

    model2, opt2 = build("1x2")
    load_checkpoint(ck_a, model2, opt2)
    save_checkpoint(ck_b, model2, opt2)
    model2.finalize()

    za, zb = np.load(ck_a), np.load(ck_b)
    keys = set(za.files) | set(zb.files)
    diffs = []
    for key in sorted(keys - {"meta"}):
        a = za[key] if key in za.files else None
        b = zb[key] if key in zb.files else None
        if a is None or b is None or a.dtype != b.dtype \
                or not np.array_equal(a, b):
            diffs.append(key)
    assert not diffs, f"state drifted across 2x1 -> 1x2: {diffs}"
    meta_b = json.loads(str(zb["meta"]))
    segs = meta_b.get("segments") or []
    assert len(segs) >= 2, segs
    return (f"{len(keys) - 1} arrays bit-equal across 2x1 -> 1x2, "
            f"{len(segs)} lineage segments")


def chaos_smoke():
    """Byzantine sign-flip under --robust_agg median on the REAL
    backend: a flipped minority must leave the robust fold's aggregate
    at the honest gradient while the plain mean is dragged off by the
    flipped mass — the engine guarantee the chaos-harness tests pin on
    the CPU mesh, exercised here on hardware."""
    from commefficient_tpu.config import Config
    from commefficient_tpu.core.rounds import (ClientStates,
                                               build_client_round)
    from commefficient_tpu.data.chaos import ChaosConfig, ChaosInjector

    W, B, d = 8, 4, 1 << 14

    def lin_loss(p, b):
        n = jnp.maximum(jnp.sum(b["mask"]), 1.0)
        loss = jnp.sum((b["c"] @ p) * b["mask"]) / n
        return loss, (loss * 0.0,)

    inj = ChaosInjector(ChaosConfig(seed=5, attack="sign_flip",
                                    byzantine_ids=(1, 5)),
                        num_clients=W)
    transform = inj.transmit_transform()
    c = np.random.RandomState(0).randn(1, 1, d).astype(np.float32)
    batch = {"c": jnp.asarray(np.broadcast_to(c, (W, B, d))),
             "mask": jnp.ones((W, B), jnp.float32)}
    flat = jnp.zeros((d,), jnp.float32)
    aggs = {}
    for agg_mode in ("none", "median"):
        cfg = Config(mode="uncompressed", error_type="none",
                     local_momentum=0.0, num_workers=W,
                     local_batch_size=B, seed=5, robust_agg=agg_mode)
        cfg.grad_size = d
        cr = jax.jit(build_client_round(cfg, lin_loss, B,
                                        transmit_transform=transform))
        res = cr(flat, ClientStates.init(cfg, W, flat), batch,
                 jnp.arange(W, dtype=jnp.int32), jax.random.PRNGKey(0),
                 1.0)
        aggs[agg_mode] = np.asarray(res.aggregated)
    honest = c[0, 0]
    scale = np.linalg.norm(honest)
    err_med = np.linalg.norm(aggs["median"] - honest) / scale
    err_plain = np.linalg.norm(aggs["none"] - honest) / scale
    # 2/8 flipped: plain mean = 0.5*honest (err 0.5); median = honest
    assert err_med < 1e-4, err_med
    assert err_plain > 0.25, err_plain
    return f"median err {err_med:.1e}; plain mean err {err_plain:.2f}"


def dp_smoke():
    """--dp sketch on the REAL backend: a zero-gradient round's
    aggregated table is pure calibrated noise (empirical std ==
    table_noise_std within 5%), one charged round at q=1 matches the
    Mironov closed form restated inline, and --dp off is lowered-text
    IDENTICAL to a build that never saw the dp knobs — privacy costs
    nothing when it is off."""
    import math

    from commefficient_tpu.config import Config
    from commefficient_tpu.core.rounds import (ClientStates,
                                               build_client_round)
    from commefficient_tpu.privacy import (build_accountant,
                                           table_noise_std)

    W, B, d = 8, 4, 1 << 14

    def lin_loss(p, b):
        n = jnp.maximum(jnp.sum(b["mask"]), 1.0)
        loss = jnp.sum((b["c"] @ p) * b["mask"]) / n
        return loss, (loss * 0.0,)

    def cfg_of(**kw):
        cfg = Config(mode="sketch", error_type="virtual",
                     local_momentum=0.0, virtual_momentum=0.9,
                     num_workers=W, local_batch_size=B, k=64,
                     num_rows=5, num_cols=16384, seed=21,
                     num_clients=W, dataset_name="CIFAR10", **kw)
        cfg.grad_size = d
        return cfg

    # calibrated noise: zero gradients -> the released table IS the
    # noise draw, so its empirical std must be the mechanism's std
    cfg = cfg_of(dp="sketch", dp_clip=1.0, dp_noise_mult=1.3)
    cr = jax.jit(build_client_round(cfg, lin_loss, B))
    batch = {"c": jnp.zeros((W, B, d), jnp.float32),
             "mask": jnp.ones((W, B), jnp.float32)}
    flat = jnp.zeros((d,), jnp.float32)
    res = cr(flat, ClientStates.init(cfg, W, flat), batch,
             jnp.arange(W, dtype=jnp.int32), jax.random.PRNGKey(0),
             1.0)
    want = table_noise_std(cfg)
    got = float(np.asarray(res.aggregated).std())
    assert abs(got - want) / want < 0.05, (got, want)

    # one charged round at q = 1 (num_clients == cohort) must equal
    # the Mironov subsampled-Gaussian closed form, restated inline
    # with math-library calls only — independent of the accountant
    acc = build_accountant(cfg)
    acc.step()
    sigma, delta = cfg.dp_noise_mult, cfg.dp_delta
    closed = min(
        a / (2.0 * sigma ** 2) + math.log1p(-1.0 / a)
        - (math.log(delta) + math.log(a)) / (a - 1)
        for a in range(2, 513))
    eps = acc.epsilon()
    assert abs(eps - closed) <= 1e-9 * closed, (eps, closed)

    # --dp off fingerprint identity: inert dp knobs must not perturb
    # the lowered round program by a single character
    texts = []
    for kw in ({}, dict(dp="off", dp_clip=9.9, dp_noise_mult=7.0)):
        c2 = cfg_of(**kw)
        f = jax.jit(build_client_round(c2, lin_loss, B))
        texts.append(f.lower(
            flat, ClientStates.init(c2, W, flat), batch,
            jnp.arange(W, dtype=jnp.int32), jax.random.PRNGKey(0),
            jnp.float32(1.0)).as_text())
    assert texts[0] == texts[1], "--dp off perturbed the round program"
    return (f"noise std {got:.4g} (calibrated {want:.4g}); "
            f"one-round eps {eps:.4g} == closed form; "
            f"dp-off program identical")


def bench_throughput():
    """Headline bench must clear the BASELINE north-star (>= 8x)."""
    import json
    import subprocess

    out = subprocess.run([sys.executable, "bench.py"],
                         capture_output=True, text=True, timeout=560)
    line = out.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["vs_baseline"] >= 8.0, line
    return line


def service_smoke():
    """Multi-tenant daemon (fedservice) on the REAL backend: one job
    driven through the FedService scheduler must be BIT-IDENTICAL to
    driving its FedModel directly (the daemon is control plane, never
    math), and a two-tenant pod must keep its ledgers isolated — one
    ``.job<j>.jsonl`` shard per tenant next to the service's own
    fairness ledger."""
    import json
    import shutil
    import tempfile

    from commefficient_tpu.config import Config
    from commefficient_tpu.fedservice import FedService, JobSpec
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)

    W, B, d, R = 8, 2, 1 << 10, 4

    def loss(params, batch, cfg):
        pred = batch["x"] @ params["w"]
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
        return l, (l * 0.0 + 1.0,)

    def job_cfg(seed):
        return Config(mode="local_topk", error_type="local",
                      local_momentum=0.9, virtual_momentum=0.0, k=8,
                      num_workers=W, local_batch_size=B,
                      num_clients=64, seed=seed)

    def builder(cfg, mesh):
        model = FedModel(None, {"w": jnp.zeros((d,), jnp.float32)},
                         loss, cfg, padded_batch_size=B, mesh=mesh)
        return model, FedOptimizer([{"lr": 0.25}], cfg, model=model)

    def batches(seed):
        rng = np.random.RandomState(seed)
        return [
            {"client_ids": rng.choice(64, W, replace=False)
             .astype(np.int32),
             "x": jnp.asarray(rng.randn(W, B, d), jnp.float32),
             "y": jnp.asarray(rng.randn(W, B), jnp.float32),
             "mask": jnp.ones((W, B), jnp.float32)}
            for _ in range(R)]

    # solo leg
    model, opt = builder(job_cfg(3), None)
    for batch in batches(7):
        model(batch)
        opt.step()
    solo = np.array(model.ps_weights)
    model.finalize()

    tmp = tempfile.mkdtemp(prefix="service_smoke_")
    try:
        led = os.path.join(tmp, "svc.jsonl")
        svc = FedService(Config(num_workers=W, local_batch_size=B,
                                num_clients=64, ledger=led))
        bs_a, bs_b = batches(7), batches(9)
        svc.admit(JobSpec("a", job_cfg(3), builder,
                          lambda r: bs_a[r], rounds=R))
        svc.admit(JobSpec("b", job_cfg(4), builder,
                          lambda r: bs_b[r], rounds=R))
        svc.run()
        daemon = svc.job_state("a")
        svc.close()
        assert np.array_equal(solo, daemon), \
            "single job through daemon != direct driver (bitwise)"
        for j in (0, 1):
            shard = f"{led}.job{j}.jsonl"
            assert os.path.exists(shard), f"missing shard {shard}"
            rounds = sum(1 for line in open(shard)
                         if json.loads(line).get("kind") == "round")
            assert rounds == R, (shard, rounds)
        svc_rounds = sum(1 for line in open(led)
                         if json.loads(line).get("kind") == "round")
        assert svc_rounds >= R, svc_rounds
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return ("1-job daemon bitwise == direct driver; 2 tenants, "
            f"{R} isolated rounds per shard")


def live_smoke():
    """Live operations plane on the REAL backend: start a fedservice
    daemon with the exporter armed, scrape /metrics mid-run and see
    per-job labeled series, trip the ``slo_burn`` rule on a
    deliberately starved tenant (backlog policy), and confirm the
    flight recorder dumped a postmortem bundle the report tool can
    round-trip."""
    import dataclasses
    import json
    import shutil
    import socket
    import tempfile
    import urllib.request

    from commefficient_tpu.config import Config
    from commefficient_tpu.fedservice import FedService, JobSpec
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)
    from commefficient_tpu.telemetry.flightrec import load_postmortem
    from commefficient_tpu.telemetry.live import shutdown_plane

    W, B, d = 8, 2, 1 << 10

    def loss(params, batch, cfg):
        pred = batch["x"] @ params["w"]
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
        return l, (l * 0.0 + 1.0,)

    def builder(cfg, mesh):
        model = FedModel(None, {"w": jnp.zeros((d,), jnp.float32)},
                         loss, cfg, padded_batch_size=B, mesh=mesh)
        return model, FedOptimizer([{"lr": 0.25}], cfg, model=model)

    def batches(seed, n):
        rng = np.random.RandomState(seed)
        return [
            {"client_ids": rng.choice(64, W, replace=False)
             .astype(np.int32),
             "x": jnp.asarray(rng.randn(W, B, d), jnp.float32),
             "y": jnp.asarray(rng.randn(W, B), jnp.float32),
             "mask": jnp.ones((W, B), jnp.float32)}
            for _ in range(n)]

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    tmp = tempfile.mkdtemp(prefix="live_smoke_")
    try:
        led = os.path.join(tmp, "svc.jsonl")
        svc_cfg = Config(num_workers=W, local_batch_size=B,
                         num_clients=64, ledger=led, live_port=port,
                         flightrec_rounds=8,
                         postmortem_dir=os.path.join(tmp, "pm"),
                         slo_starvation=1.0, slo_window=4,
                         slo_fast_window=2, alarm_slo_burn=1.0)
        # NB: no live_port here — the daemon propagates its own
        # plane knobs to every tenant at admission
        job_cfg = Config(mode="local_topk", error_type="local",
                         local_momentum=0.9, virtual_momentum=0.0,
                         k=8, num_workers=W, local_batch_size=B,
                         num_clients=64, seed=3)
        svc = FedService(svc_cfg, policy="backlog")
        bs_a, bs_b = batches(7, 6), batches(9, 2)
        svc.admit(JobSpec("a", job_cfg, builder,
                          lambda r: bs_a[r] if r < 6 else None,
                          rounds=6))
        svc.admit(JobSpec("b", dataclasses.replace(job_cfg, seed=4),
                          builder,
                          lambda r: bs_b[r] if r < 2 else None,
                          rounds=2))
        for _ in range(8):
            svc.tick()
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) \
            .read().decode()
        series = [l for l in body.splitlines()
                  if l and not l.startswith("#")]
        for want in ('commeff_rounds_total{job="0"',
                     'commeff_rounds_total{job="1"',
                     'commeff_rounds_total{job="service"}',
                     "commeff_round_seconds",
                     "commeff_job_backlog_total",
                     "commeff_alarms_total"):
            assert any(want in l for l in series), (want, series)
        bundle_path = svc.flightrec.last_bundle
        assert bundle_path and os.path.exists(bundle_path), \
            "slo_burn fired but no postmortem bundle dumped"
        svc.close()
        bundle, problems = load_postmortem(bundle_path)
        assert not problems, problems
        assert bundle["rule"] == "slo_burn", bundle["rule"]
        # close()-time alarm backfill: the service ledger's summary
        # record must carry the run's slo_burn fire count
        fired = next(
            (rec.get("alarm_fired") for rec in
             map(json.loads, open(led)) if rec.get("kind") == "summary"
             and rec.get("alarm_fired")), None)
        assert fired and fired.get("slo_burn", 0) >= 1, fired
    finally:
        shutdown_plane()
        shutil.rmtree(tmp, ignore_errors=True)
    return (f"scraped {len(series)} live series; slo_burn tripped, "
            f"postmortem bundle round-trips ({bundle['reason']})")


def causal_smoke():
    """--causal_trace on the REAL backend: a traced FedModel run
    stamps every round record with a span DAG that closes (no orphan
    parents), whose critical path reproduces the round wall within
    clock tolerance — and the flag is provably inert off: the lowered
    client-round program is byte-identical with the knob set (the
    spans live entirely on the host)."""
    import dataclasses
    import json
    import shutil
    import tempfile

    from commefficient_tpu.config import Config
    from commefficient_tpu.core.rounds import (ClientStates,
                                               build_client_round)
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)
    from commefficient_tpu.telemetry.causal import assemble_traces
    from commefficient_tpu.telemetry.critpath import (CLOCK_TOLERANCE,
                                                      critical_path)

    W, B, d, R = 8, 2, 1 << 10, 3

    def loss(params, batch, cfg):
        pred = batch["x"] @ params["w"]
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
        return l, (l * 0.0 + 1.0,)

    cfg = Config(mode="local_topk", error_type="local",
                 local_momentum=0.9, virtual_momentum=0.0, k=8,
                 num_workers=W, local_batch_size=B, num_clients=64,
                 seed=3, causal_trace=True)

    # 1. HLO identity: the knob must not perturb the compiled program
    def lin_loss(p, b):
        pred = b["x"] @ p
        n = jnp.maximum(jnp.sum(b["mask"]), 1.0)
        l = jnp.sum((pred - b["y"]) ** 2 * b["mask"]) / n
        return l, (l * 0.0 + 1.0,)

    lcfg = dataclasses.replace(cfg, causal_trace=False, grad_size=d)

    def lower(c):
        ps = jax.ShapeDtypeStruct((d,), jnp.float32)
        cs = jax.eval_shape(
            lambda: ClientStates.init(c, cfg.num_clients,
                                      jnp.zeros((d,), jnp.float32)))
        batch = {"x": jax.ShapeDtypeStruct((W, B, d), jnp.float32),
                 "y": jax.ShapeDtypeStruct((W, B), jnp.float32),
                 "mask": jax.ShapeDtypeStruct((W, B), jnp.float32)}
        return jax.jit(build_client_round(c, lin_loss, B)).lower(
            ps, cs, batch, jax.ShapeDtypeStruct((W,), jnp.int32),
            jax.ShapeDtypeStruct((2,), jnp.uint32),
            jax.ShapeDtypeStruct((), jnp.float32)).as_text()

    assert lower(dataclasses.replace(lcfg, causal_trace=True)) \
        == lower(lcfg), "--causal_trace perturbed the lowered HLO"

    # 2. traced run: DAG closes, critical path == wall
    rng = np.random.RandomState(7)
    tmp = tempfile.mkdtemp(prefix="causal_smoke_")
    try:
        led = os.path.join(tmp, "run.jsonl")
        rcfg = dataclasses.replace(cfg, ledger=led)
        model = FedModel(None,
                         {"w": jnp.zeros((d,), jnp.float32)}, loss,
                         rcfg, padded_batch_size=B, mesh=None)
        opt = FedOptimizer([{"lr": 0.25}], rcfg, model=model)
        for _ in range(R):
            model({"client_ids": rng.choice(64, W, replace=False)
                   .astype(np.int32),
                   "x": jnp.asarray(rng.randn(W, B, d), jnp.float32),
                   "y": jnp.asarray(rng.randn(W, B), jnp.float32),
                   "mask": jnp.ones((W, B), jnp.float32)})
            opt.step()
        model.finalize()
        records = [json.loads(line) for line in open(led)]
        rounds = [r for r in records if r.get("kind") == "round"]
        assert len(rounds) == R and all(
            isinstance(r.get("causal"), dict) for r in rounds), rounds
        worst = 0.0
        for rec in rounds:
            crit = critical_path(rec["causal"],
                                 rec.get("device_time"))
            gap = abs(sum(crit["buckets"].values()) - crit["wall"])
            worst = max(worst, gap)
            assert gap <= CLOCK_TOLERANCE, (gap, crit)
        traces = assemble_traces(records)
        orphans = {t: d_["orphans"] for t, d_ in traces.items()
                   if d_["orphans"]}
        assert len(traces) == R and not orphans, orphans
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return (f"HLO identical off/on; {R} traced rounds closed, "
            f"critpath==wall to {worst:.1e}s")


def main():
    print(f"devices: {jax.devices()}")
    check("pallas_vs_xla_sketch_parity", pallas_parity)
    check("bf16_flagship_round", bf16_round_trains)
    check("probe_smoke", probe_smoke)
    check("quant_smoke", quant_smoke)
    check("overlap_smoke", overlap_smoke)
    check("async_smoke", async_smoke)
    check("service_smoke", service_smoke)
    check("autopilot_smoke", autopilot_smoke)
    check("audit_smoke", audit_smoke)
    check("flowlint_smoke", flowlint_smoke)
    check("trace_smoke", trace_smoke)
    check("scaling_smoke", scaling_smoke)
    check("mesh2d_smoke", mesh2d_smoke)
    check("elastic_smoke", elastic_smoke)
    check("flash_attention_parity", flash_attention_parity)
    check("chaos_smoke", chaos_smoke)
    check("dp_smoke", dp_smoke)
    check("live_smoke", live_smoke)
    check("causal_smoke", causal_smoke)
    check("bench_vs_baseline", bench_throughput)
    if FAILED:
        print(f"\n{len(FAILED)} check(s) failed: {FAILED}")
        sys.exit(1)
    print("\nall hardware checks passed")


if __name__ == "__main__":
    main()
