"""Round-5 chip queue, part 2 (run after the local_topk lr-0.1 anchor):

1. local_topk at 20% participation (the last unexplored dial for an
   anchor row where the mode learns): 100 clients x cpc3, w20, lr 0.1.
   Full-participation runs are flat at BOTH lr 0.4 and 0.1, so LR is
   ruled out; averaging 100 mostly-disjoint k=50000 masks shrinks the
   per-coordinate step ~100x, and participation is the remaining
   lever the round-3 small-scale evidence (localtopk_cpc3_w5.log,
   acc 1.0 at 50% participation of 10 clients) says matters.
2. FLCE A/B: flagship (4x2x2x256) and 8x (8x8x2x256) federated sketch
   rounds, --fused_ce off vs on; plus bare-model 8x control.
3. T=1024 long-context federated: sketch mode, 2x4x2x1024, XLA vs
   flash attention, fused CE on/off — the verdict-5 end-to-end run.

Everything prints to stdout; anchor logs land in runs/.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable


def run(cmd):
    print("==>", " ".join(cmd), flush=True)
    r = subprocess.run(cmd, cwd=REPO, check=False,
                       capture_output=True, text=True)
    out = (r.stdout or "") + (r.stderr or "")
    for line in out.splitlines():
        if line.startswith("{") or "==" in line or "acc" in line:
            print(line, flush=True)
    return out


def main():
    # 1. local_topk participation probe
    run([PY, "scripts/anchor24.py", "--modes", "local_topk",
         "--num_clients", "100", "--lr_scale", "0.1", "--seed", "21",
         "--suffix", "_c100cpc3w20_lr01",
         "--extra",
         "--client_chunk 10 --classes_per_client 3 --num_workers 20"])

    # 2. FLCE end-to-end A/Bs
    for geom in (["--clients", "4", "--examples", "2"],
                 ["--clients", "8", "--examples", "8"]):
        for fused in ("off", "on"):
            run([PY, "scripts/gpt2_bench.py", "--mode", "sketch",
                 "--rounds", "10", "--reps", "3",
                 "--fused_ce", fused] + geom)
    for fused in ("off", "on"):
        run([PY, "scripts/gpt2_bench.py", "--mode", "bare",
             "--clients", "8", "--examples", "8",
             "--rounds", "10", "--reps", "3", "--fused_ce", fused])

    # 3. T=1024 federated long-context: attn x fused matrix
    for attn in ("xla", "flash"):
        for fused in ("off", "on"):
            run([PY, "scripts/gpt2_bench.py", "--mode", "sketch",
                 "--clients", "2", "--examples", "4",
                 "--seq", "1024", "--rounds", "5", "--reps", "3",
                 "--attn_impl", attn, "--fused_ce", fused])
    print("QUEUE2 DONE", flush=True)


if __name__ == "__main__":
    main()
