#!/usr/bin/env bash
# ImageNet / FixupResNet50 federated run — the working TPU counterpart
# of the reference's imagenet.sh (which passes --mixup/--mixup_alpha/
# --supervised flags its own parse_args does not define; those are
# dropped here). Reference config: 7 workers / 7 clients iid, local
# batch 64, virtual momentum 0.9, wd 1e-4, error_type virtual,
# mode uncompressed (imagenet.sh:2-21).
set -euo pipefail

DATASET_DIR=${DATASET_DIR:-./data/imagenet}

python -m commefficient_tpu.train.cv_train \
    --dataset_name ImageNet \
    --dataset_dir "$DATASET_DIR" \
    --model FixupResNet50 \
    --mode uncompressed \
    --error_type virtual \
    --iid \
    --num_clients 7 \
    --num_workers 7 \
    --local_batch_size 64 \
    --valid_batch_size 64 \
    --local_momentum 0 \
    --virtual_momentum 0.9 \
    --weight_decay 1e-4 \
    --num_epochs 24 \
    --pivot_epoch 5 \
    --lr_scale 0.4 \
    --k 1000000 \
    --num_rows 1 \
    --num_cols 10000000 \
    --mixup \
    --mixup_alpha 0.2 \
    "$@"
