"""Recovery-quality experiment for sublane-quantized rotations.

``CountSketch(rot_lanes=L)`` restricts per-(row, chunk) rotations to
multiples of L so the Pallas kernels roll sublane-only (a single VPU
op instead of five). The cost is a heavier collision tail: pairs with
equal lane offset collide with probability L/c instead of 1/c. This
script measures what that does to FetchSGD-relevant recovery on
synthetic heavy-hitter data BEFORE any default changes:

- top-k recovery rate: fraction of the true top-k coordinates found by
  ``unsketch(k)``;
- relative L2 error of the recovered heavy-hitter values;
- l2estimate relative error.

Usage:
  python scripts/rot_quality.py [--d 6600000] [--c 524288] [--r 5]
      [--k 50000] [--hot 50000] [--seeds 5] [--rot_lanes 0,1024]
      [--cpu]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def one_trial(d, c, r, k, hot, seed, rot_lanes, backend):
    from commefficient_tpu.ops.sketch import CountSketch
    cs = CountSketch(d=d, c=c, r=r, seed=seed, backend=backend,
                     rot_lanes=rot_lanes)
    rng = np.random.RandomState(seed)
    v = rng.randn(d).astype(np.float32)  # heavy gaussian tail
    hot_idx = rng.choice(d, hot, replace=False)
    v[hot_idx] += np.sign(rng.randn(hot)) * 10.0  # planted heavy mass
    vj = jnp.asarray(v)
    table = jax.jit(cs.sketch)(vj)

    dense, idx, vals = cs.unsketch(table, k, with_support=True)
    sel = set(np.asarray(idx).tolist())
    true_idx = np.argsort(-np.abs(v))[:k]
    recovery = len(sel & set(true_idx.tolist())) / k

    # value error on the coordinates actually selected
    est = np.asarray(vals)
    truth = v[np.asarray(idx)]
    val_err = float(np.linalg.norm(est - truth)
                    / max(np.linalg.norm(truth), 1e-9))

    l2 = float(CountSketch.l2estimate(table))
    l2_err = abs(l2 - float(np.linalg.norm(v))) / float(np.linalg.norm(v))
    return recovery, val_err, l2_err


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--d", type=int, default=6_600_000)
    ap.add_argument("--c", type=int, default=524288)
    ap.add_argument("--r", type=int, default=5)
    ap.add_argument("--k", type=int, default=50000)
    ap.add_argument("--hot", type=int, default=50000)
    ap.add_argument("--seeds", type=int, default=5)
    ap.add_argument("--rot_lanes", default="0,1024")
    ap.add_argument("--backend", default="auto")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    out = {"geometry": {"d": args.d, "c": args.c, "r": args.r,
                        "k": args.k, "hot": args.hot,
                        "seeds": args.seeds}}
    for rl in [int(x) for x in args.rot_lanes.split(",")]:
        recs, verrs, l2errs = [], [], []
        for s in range(args.seeds):
            rec, verr, l2e = one_trial(args.d, args.c, args.r, args.k,
                                       args.hot, 100 + s, rl,
                                       args.backend)
            recs.append(rec)
            verrs.append(verr)
            l2errs.append(l2e)
        out[f"rot_lanes_{rl}"] = {
            "topk_recovery_mean": round(float(np.mean(recs)), 4),
            "topk_recovery_min": round(float(np.min(recs)), 4),
            "val_rel_err_mean": round(float(np.mean(verrs)), 4),
            "l2est_rel_err_mean": round(float(np.mean(l2errs)), 4),
        }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
