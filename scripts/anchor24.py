"""Discriminating long-horizon convergence anchor: the reference
24-epoch default schedule (PiecewiseLinear 0 -> lr 0.4 @ pivot 5 -> 0
@ 24, wd 5e-4, bf16 — reference utils.py:153-163, cv_train.py:394-406)
at the FetchSGD paper federation geometry (10 000 one-class clients ×
5 images, 100 workers/round), on the class-overlap Synthetic task
(--synthetic_separation 0.025: Bayes ceiling ~0.86,
FedSynthetic.bayes_accuracy) — sub-1.0 ceiling, so the anchor
discriminates accuracy instead of saturating from epoch 1 (round-3
review weak #1). Measured orderings (BENCHMARKS.md "24-epoch
mode-ordering anchor"): at the SHARED reference peak (--lr_scale
0.4), true_topk ≈ sketch ≫ fedavg ≈ uncompressed ≫
local_topk-at-one-class (chance). The round-5 per-mode LR sweep
showed the dense-mode gap was an over-hot-LR artifact, not a
compression fact — the round-3/4 "top-k as denoiser" reading of
that gap is RETRACTED: at their own best peak (0.1) uncompressed
tails 0.281 and fedavg 0.290 vs sketch's 0.283, i.e. the paper's
"sketch ≈ uncompressed" quality parity holds once every mode runs
at its own best LR. What IS mode-robust: the top-k family tolerates
the reference 0.4 schedule (selection + error feedback damp the
effective step) while the dense updates diverge there
(uncompressed final test loss 2.10/3.55/3.75 at lr 0.1/0.2/0.4,
monotone in LR) — an
operational robustness advantage of sketch/true_topk, not a
quality gap.

Usage:
  python scripts/anchor24.py [--modes sketch,uncompressed,...]
      [--seed 21] [--epochs 24] [--logdir runs]
Runs modes sequentially (one chip), writes runs/anchor24_<mode>_s<seed>.log,
prints a final ordering summary with the Bayes ceiling.
"""

import argparse
import contextlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

MODE_FLAGS = {
    "sketch": ["--mode", "sketch", "--error_type", "virtual",
               "--local_momentum", "0", "--virtual_momentum", "0.9",
               "--k", "50000", "--num_rows", "5",
               "--num_cols", "524288"],
    "true_topk": ["--mode", "true_topk", "--error_type", "virtual",
                  "--local_momentum", "0", "--virtual_momentum", "0.9",
                  "--k", "50000"],
    "uncompressed": ["--mode", "uncompressed", "--error_type", "none",
                     "--local_momentum", "0",
                     "--virtual_momentum", "0.9"],
    "local_topk": ["--mode", "local_topk", "--error_type", "local",
                   "--local_momentum", "0.9", "--k", "50000"],
    "fedavg": ["--mode", "fedavg", "--error_type", "none",
               "--local_momentum", "0", "--virtual_momentum", "0.9",
               "--local_batch_size", "-1"],
}


def common_flags(args):
    flags = [
        "--dataset_name", "Synthetic",
        "--num_clients", str(args.num_clients),
        "--synthetic_per_class", "5000",
        "--synthetic_separation", str(args.separation),
        "--synthetic_num_val", "2000",
        "--num_workers", "100",
        "--num_epochs", str(args.epochs),
        "--lr_scale", str(args.lr_scale), "--pivot_epoch", "5",
        "--bf16", "--pipeline_depth", "4",
        "--seed", str(args.seed),
    ]
    return flags


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--modes",
                    default="sketch,uncompressed,true_topk,"
                            "local_topk,fedavg")
    ap.add_argument("--seed", type=int, default=21)
    ap.add_argument("--epochs", type=float, default=24)
    ap.add_argument("--separation", type=float, default=0.025)
    # the reference default schedule peaks at 0.4 — the right scale
    # for the top-k family here, but the round-4 review showed the
    # DENSE modes (uncompressed/fedavg) diverging late at it on this
    # task; sweep them at their own best LR before stating orderings
    ap.add_argument("--lr_scale", type=float, default=0.4)
    # local_topk's per-client dense error/momentum state is
    # (num_clients, d) f32 — 263 GB at the 10 000-client paper
    # geometry, infeasible for ANY single machine (the reference's
    # host-shm design included, fed_aggregator.py:116-129). Run that
    # mode at the largest fitting federation — 100 clients x 500
    # images with --extra "--client_chunk 10" (the 2 x 2.6 GB state
    # buffers still double-buffer through the scan carry; 250 clients
    # OOMed) — and footnote the geometry change.
    ap.add_argument("--num_clients", type=int, default=10000)
    ap.add_argument("--suffix", default="",
                    help="log-name suffix, e.g. _c100")
    ap.add_argument("--extra", default="",
                    help="extra cv_train flags appended to every "
                    "mode, e.g. '--client_chunk 10'")
    ap.add_argument("--logdir", default="runs")
    args = ap.parse_args()

    from commefficient_tpu.data.synthetic import FedSynthetic
    from commefficient_tpu.train import cv_train

    ceiling = FedSynthetic(
        "", "Synthetic", train=False, do_iid=False,
        num_clients=args.num_clients, per_class=5000, num_val=2000,
        separation=args.separation, seed=args.seed).bayes_accuracy()
    print(f"Bayes ceiling at separation {args.separation}: "
          f"{ceiling:.4f}", flush=True)

    os.makedirs(args.logdir, exist_ok=True)
    summary = {}
    for mode in args.modes.split(","):
        flags = common_flags(args) + MODE_FLAGS[mode]
        if mode != "fedavg":
            flags += ["--local_batch_size", "5"]
        if args.extra:
            import shlex
            flags += shlex.split(args.extra)
        # (fedavg's -1 = local SGD over the client's full 5-image
        # shard is in its MODE_FLAGS)
        log_path = os.path.join(
            args.logdir,
            f"anchor24_{mode}{args.suffix}_s{args.seed}.log")
        print(f"== {mode} -> {log_path}", flush=True)
        # stream to the file as the run goes: a mid-run kill keeps
        # the epochs so far instead of discarding a buffered log
        # line-buffered: the epoch rows land as they print (a
        # block-buffered redirect holds ~60 epochs back)
        with open(log_path, "w", buffering=1) as f:
            f.write(" ".join(flags) + "\n")
            f.flush()
            try:
                with contextlib.redirect_stdout(f):
                    results = cv_train.main(flags)
            except (KeyboardInterrupt, SystemExit):
                raise  # an interrupted sweep must stop, not continue
            except Exception as e:  # record divergence/abort, go on
                results = None
                f.write(f"\nABORTED: {e!r}\n")
        if results:
            tail = [r["test_acc"] for r in results[-5:]]
            summary[mode] = {
                "final_acc": results[-1]["test_acc"],
                # mean of the last 5 epochs: the ordering statistic —
                # robust to single-epoch jitter, unlike a lone final
                # accuracy (the fp-fragility that motivated this
                # anchor in the first place)
                "tail_acc": sum(tail) / len(tail),
                "best_acc": max(r["test_acc"] for r in results),
                "final_loss": results[-1]["train_loss"],
                "epochs": len(results),
            }
        else:
            summary[mode] = {"final_acc": float("nan"),
                             "tail_acc": float("nan"),
                             "best_acc": float("nan"),
                             "final_loss": float("nan"), "epochs": 0}
        print(f"   {mode}: {summary[mode]}", flush=True)

    print(json.dumps({"bayes_ceiling": ceiling, "seed": args.seed,
                      "separation": args.separation,
                      "modes": summary}), flush=True)


if __name__ == "__main__":
    sys.exit(main())
