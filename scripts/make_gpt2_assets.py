"""Fabricate full-size GPT-2 learning-run assets (zero-egress stand-ins
for the reference's downloads):

- a 50257-entry byte-level BPE vocab (``data/tokenizer.py
  fabricate_bpe_vocab``) — the real vocabulary's *geometry* without the
  real files;
- a random-init HF-layout GPT-2 124M checkpoint (``pytorch_model.bin``
  via ``transformers.GPT2LMHeadModel``) so training starts through the
  same disk path the reference uses for the pretrained model
  (reference gpt2_train.py:262-285);
- a learnable persona-correlated PersonaChat-format corpus
  (``data/fed_persona.py generate_learnable_personachat``).

Usage:
  python scripts/make_gpt2_assets.py --out runs/gpt2_learn \
      [--personalities 1000] [--dialogs 4] [--utterances 5] [--seed 0]

Writes ``<out>/ckpt`` (vocab + weights) and ``<out>/data`` (corpus).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", required=True)
    p.add_argument("--personalities", type=int, default=1000)
    p.add_argument("--dialogs", type=int, default=4)
    p.add_argument("--utterances", type=int, default=5)
    p.add_argument("--candidates", type=int, default=5)
    p.add_argument("--signature", type=int, default=24)
    p.add_argument("--val_dialogs", type=int, default=100)
    p.add_argument("--words", type=int, default=8000)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--skip_ckpt", action="store_true",
                   help="only (re)generate vocab + corpus")
    p.add_argument("--val_from_train_sigs", action="store_true",
                   help="validation dialogs reuse TRAIN personalities "
                   "(fresh sentences) — the easier seen-persona "
                   "evaluation tier; train split stays byte-identical "
                   "for a given seed/word budget")
    p.add_argument("--distractor_disjoint", action="store_true",
                   help="rejection-sample distractor personas so their "
                   "signatures share no words with the gold persona "
                   "(Bayes-1.0 lexical-overlap MC task)")
    args = p.parse_args()

    ckpt_dir = os.path.join(args.out, "ckpt")
    data_dir = os.path.join(args.out, "data")

    from commefficient_tpu.data.fed_persona import \
        generate_learnable_personachat
    from commefficient_tpu.data.tokenizer import fabricate_bpe_vocab

    words = fabricate_bpe_vocab(ckpt_dir, vocab_size=50257,
                                num_words=args.words, seed=args.seed)
    print(f"vocab: 50257 entries, {len(words)} single-token words "
          f"-> {ckpt_dir}")

    generate_learnable_personachat(
        data_dir, words, num_personalities=args.personalities,
        dialogs_per_personality=args.dialogs,
        utterances_per_dialog=args.utterances,
        num_candidates=args.candidates, signature_size=args.signature,
        num_val_dialogs=args.val_dialogs, seed=args.seed,
        val_from_train_sigs=args.val_from_train_sigs,
        distractor_disjoint=args.distractor_disjoint)
    n_train = args.personalities * args.dialogs * args.utterances
    print(f"corpus: {n_train} train utterances, "
          f"{args.val_dialogs * args.utterances} val -> {data_dir}")

    if args.skip_ckpt:
        return
    bin_path = os.path.join(ckpt_dir, "pytorch_model.bin")
    if os.path.exists(bin_path):
        print(f"{bin_path} exists; keeping")
        return
    import torch
    from transformers import GPT2Config as HFConfig
    from transformers import GPT2LMHeadModel

    torch.manual_seed(args.seed)
    hf_cfg = HFConfig(vocab_size=50257, n_positions=1024, n_embd=768,
                      n_layer=12, n_head=12)
    model = GPT2LMHeadModel(hf_cfg)
    torch.save(model.state_dict(), bin_path)
    n = sum(p.numel() for p in model.parameters())
    print(f"checkpoint: {n / 1e6:.1f}M params (random init, "
          f"seed {args.seed}) -> {bin_path}")


if __name__ == "__main__":
    main()
