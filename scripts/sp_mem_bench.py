"""Compiled temp-memory table for the SP round's LM-loss formulations
(BENCHMARKS.md "Sequence-parallel long-context memory").

Runs on the 8-device virtual CPU mesh; reports
``compile().memory_analysis().temp_size_in_bytes`` per device for the
chunked vocab CE at several ``tokens_per_chunk`` settings, including
the dense-equivalent upper bound (chunk = full local shard, which
materialises the whole (B·N, T_local, V) logits block in one chunk).

Usage:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/sp_mem_bench.py [--seq 4096] [--clients 2] \
      [--seq_shards 4] [--vocab 50262] [--chunks 0,256,1024,full]
"""

import argparse

import jax


def main():
    jax.config.update("jax_platforms", "cpu")
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--clients", type=int, default=2)
    ap.add_argument("--seq_shards", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=50262)
    ap.add_argument("--examples", type=int, default=1)
    ap.add_argument("--candidates", type=int, default=2)
    ap.add_argument("--chunks", default="0,128,256,1024,full",
                    help="comma list of tokens_per_chunk values; "
                    "0 = auto default, 'full' = whole local shard "
                    "(dense-equivalent)")
    args = ap.parse_args()

    import jax.numpy as jnp
    import numpy as np

    from commefficient_tpu.core.rounds_sp import (build_sp_gpt2_round,
                                                  make_sp_mesh,
                                                  shift_lm_labels)
    from commefficient_tpu.models.gpt2 import (GPT2Config,
                                               GPT2DoubleHeads)
    from commefficient_tpu.ops.vec import flatten_params

    W, B, N, T = (args.clients, args.examples, args.candidates,
                  args.seq)
    T_local = T // args.seq_shards
    # narrow 2-layer config isolates the vocab head (round-3 setup)
    cfg = GPT2Config(vocab_size=args.vocab, n_positions=T, n_embd=256,
                     n_layer=2, n_head=4, dtype=jnp.bfloat16)
    mesh = make_sp_mesh(args.clients, args.seq_shards)

    dense = GPT2DoubleHeads(cfg)
    ids0 = jnp.zeros((1, N, 8), jnp.int32)
    params = dense.init(jax.random.PRNGKey(0), ids0,
                        jnp.zeros((1, N), jnp.int32), ids0)["params"]
    flat, unravel = flatten_params(params)

    rng = np.random.RandomState(0)
    batch = {
        "input_ids": jnp.asarray(
            rng.randint(0, args.vocab, (W, B, N, T)), jnp.int32),
        "token_type_ids": jnp.asarray(
            rng.randint(0, 2, (W, B, N, T)), jnp.int32),
        "shifted_labels": shift_lm_labels(jnp.asarray(
            rng.randint(0, args.vocab, (W, B, N, T)), jnp.int32)),
        "mc_token_ids": jnp.full((W, B, N), T - 1, jnp.int32),
        "mc_labels": jnp.full((W, B), N - 1, jnp.int32),
        "mask": jnp.ones((W, B), jnp.float32),
    }

    full = B * N * T_local
    print(f"geometry: {W} clients x {args.seq_shards} seq shards, "
          f"T={T} (T_local={T_local}), vocab={args.vocab}, "
          f"E={B * N}/shard")
    for spec in args.chunks.split(","):
        tpc = full if spec == "full" else int(spec)
        fn = jax.jit(build_sp_gpt2_round(cfg, mesh, unravel,
                                         tokens_per_chunk=tpc))
        compiled = fn.lower(flat, batch).compile()
        ma = compiled.memory_analysis()
        temp = ma.temp_size_in_bytes  # per-device executable stats
        label = {0: "auto(256)", full: f"full-shard({full})"}.get(
            tpc, str(tpc))
        print(f"  tokens_per_chunk {label:>18}: "
              f"{temp / 2**30:.2f} GB temp/device")


if __name__ == "__main__":
    main()
