"""Host data-plane benchmarks at natural-partition scale.

Round-1 review: "17,568-client PERSONA prep and 3500-writer EMNIST
packing have never been timed". This script fabricates synthetic
archives at the natural *client counts* (dialog/image payloads scaled
down — the client-count axis is what stresses the host: file counts,
cumsum sizes, fd behavior) and times:

- PERSONA: archive parse + per-client split (prepare_datasets),
  dataset construction, item access rate, FedSampler round rate
- FEMNIST: LEAF json parse + packed-memmap write (prepare_datasets),
  item access rate

Usage:  python scripts/host_scale_bench.py [--persona_clients 17568]
        [--emnist_writers 3500] [--emnist_images 20] [--workdir DIR]

Results are recorded in BENCHMARKS.md ("Host data-plane at natural
scale").
"""

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import time


def bench_persona(root, num_clients):
    from commefficient_tpu.data.fed_persona import (
        FedPERSONA, generate_synthetic_personachat)
    from commefficient_tpu.data.fed_sampler import FedSampler
    from commefficient_tpu.data.tokenizer import (ByteTokenizer,
                                                  SPECIAL_TOKENS)

    d = os.path.join(root, "persona")
    os.makedirs(d, exist_ok=True)

    t0 = time.time()
    # the tests' archive generator at natural client count, ~natural
    # candidate count
    generate_synthetic_personachat(d, num_personalities=num_clients,
                                   dialogs_per_personality=1,
                                   utterances_per_dialog=3,
                                   num_candidates=20)
    gen_s = time.time() - t0

    tok = ByteTokenizer()
    tok.add_special_tokens(SPECIAL_TOKENS)

    t0 = time.time()
    ds = FedPERSONA(tok, 2, 2, 1, d, "PERSONA", train=True)
    prep_s = time.time() - t0  # includes prepare_datasets (first run)

    t0 = time.time()
    ds2 = FedPERSONA(tok, 2, 2, 1, d, "PERSONA", train=True)
    load_s = time.time() - t0  # stats-only reload

    n_items = len(ds2)
    n_access = min(5000, n_items)
    idxs = random.Random(1).sample(range(n_items), n_access)
    t0 = time.time()
    for i in idxs:
        ds2[i]
    access_s = time.time() - t0

    sampler = FedSampler(ds2, 16, 4, seed=0)
    t0 = time.time()
    rounds = 0
    for spec in sampler:
        rounds += 1
        if rounds >= 200:
            break
    sample_s = time.time() - t0

    return {
        "persona_clients": num_clients,
        "persona_items": n_items,
        "persona_archive_gen_s": round(gen_s, 2),
        "persona_prepare_s": round(prep_s, 2),
        "persona_reload_s": round(load_s, 3),
        "persona_item_access_per_s": round(n_access / access_s),
        "persona_sampler_rounds_per_s": round(200 / sample_s),
    }


def bench_emnist(root, writers, images_per_writer):
    from commefficient_tpu.data.fed_emnist import FedEMNIST

    d = os.path.join(root, "emnist")
    for sub in ("train", "test"):
        os.makedirs(os.path.join(d, sub), exist_ok=True)
    rng = random.Random(0)

    t0 = time.time()
    # LEAF-format shards: ~100 writers per json file like LEAF emits
    per_shard = 100
    for shard in range(0, writers, per_shard):
        user_data = {}
        for w in range(shard, min(shard + per_shard, writers)):
            n = images_per_writer
            user_data[f"w{w}"] = {
                "x": [[rng.random() for _ in range(784)]
                      for _ in range(n)],
                "y": [rng.randrange(62) for _ in range(n)],
            }
        blob = {"users": list(user_data), "user_data": user_data}
        with open(os.path.join(d, "train",
                               f"all_data_{shard}.json"), "w") as f:
            json.dump(blob, f)
    # small test split
    user_data = {f"t{w}": {"x": [[0.0] * 784 for _ in range(4)],
                           "y": [rng.randrange(62) for _ in range(4)]}
                 for w in range(20)}
    with open(os.path.join(d, "test", "all_data_0.json"), "w") as f:
        json.dump({"users": list(user_data),
                   "user_data": user_data}, f)
    gen_s = time.time() - t0

    t0 = time.time()
    ds = FedEMNIST(d, "EMNIST", train=True)
    prep_s = time.time() - t0

    n_items = len(ds)
    n_access = min(20000, n_items)
    idxs = random.Random(1).sample(range(n_items), n_access)
    t0 = time.time()
    for i in idxs:
        ds[i]
    access_s = time.time() - t0

    return {
        "emnist_writers": writers,
        "emnist_images": n_items,
        "emnist_leaf_gen_s": round(gen_s, 2),
        "emnist_prepare_s": round(prep_s, 2),
        "emnist_item_access_per_s": round(n_access / access_s),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--persona_clients", type=int, default=17568)
    ap.add_argument("--emnist_writers", type=int, default=3500)
    ap.add_argument("--emnist_images", type=int, default=20)
    ap.add_argument("--workdir", type=str, default=None)
    args = ap.parse_args()

    root = args.workdir or tempfile.mkdtemp(prefix="host_scale_")
    print(f"workdir: {root}", file=sys.stderr)
    out = {}
    try:
        out.update(bench_persona(root, args.persona_clients))
        out.update(bench_emnist(root, args.emnist_writers,
                                args.emnist_images))
    finally:
        if args.workdir is None:
            shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
