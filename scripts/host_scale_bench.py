"""Host data-plane benchmarks at natural-partition scale.

Round-1 review: "17,568-client PERSONA prep and 3500-writer EMNIST
packing have never been timed". This script fabricates synthetic
archives at the natural *client counts* (dialog/image payloads scaled
down — the client-count axis is what stresses the host: file counts,
cumsum sizes, fd behavior) and times:

- PERSONA: archive parse + per-client split (prepare_datasets),
  dataset construction, item access rate, FedSampler round rate
- FEMNIST: LEAF json parse + packed-memmap write (prepare_datasets),
  item access rate
- clientstore: the host-resident client-state store
  (commefficient_tpu/clientstore) — per-round throughput vs the dense
  device placement at a matched moderate population, plus the scale
  axis the device placement cannot reach: local_topk/fedavg rounds at
  --store_scale_clients (default 1M) simulated clients under a FIXED
  --store_budget_mb arena, reporting ``clients_resident_max_local_topk``
  (peak arena rows — the store's working set, independent of the
  population).
- arrival: round throughput under a data/chaos.py seeded arrival
  process (correlated dropout bursts + straggler stalls) vs the calm
  loader — the host-side cost of ragged rounds, with the replayed
  schedule's burst/alive statistics (ArrivalSchedule.replay_stats).
- async: buffered asynchronous serving (asyncfed) vs the synchronous
  barrier, both replaying the same churny ArrivalSchedule at
  --store_scale_clients host-resident clients — host-gap share
  (wall minus device-dispatch span, as a fraction of wall) for each
  leg, plus the buffered leg's staleness statistics. With --ledger
  the buffered leg writes the telemetry ledger and a runs/ manifest,
  so scripts/perf_gate.py gates it under its a<K> topology key.

- service: the fedservice daemon multiplexing --service_jobs (>= 3)
  independent tenants over one pod, each replaying its own seeded
  churny chaos arrival trace at --service_clients_per_job host-store
  clients (>= 1M in aggregate at the defaults). Headline: aggregate
  clients served per second per pod. With --ledger (and
  --only service) the numeric record is gated by scripts/perf_gate.py
  under the run's j<J> topology key — no cross-J fallback.

Usage:  python scripts/host_scale_bench.py [--persona_clients 17568]
        [--emnist_writers 3500] [--emnist_images 20] [--workdir DIR]
        [--only all|persona|emnist|clientstore|arrival|async|service]
        [--store_scale_clients 1000000] [--store_budget_mb 4]
        [--arrival_rounds 40] [--arrival_burst_start 0.2]
        [--async_k 4] [--async_alpha 0.5] [--ledger runs/async.jsonl]

Results are recorded in BENCHMARKS.md ("Host data-plane at natural
scale" and "Host client store").
"""

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def bench_persona(root, num_clients):
    from commefficient_tpu.data.fed_persona import (
        FedPERSONA, generate_synthetic_personachat)
    from commefficient_tpu.data.fed_sampler import FedSampler
    from commefficient_tpu.data.tokenizer import (ByteTokenizer,
                                                  SPECIAL_TOKENS)

    d = os.path.join(root, "persona")
    os.makedirs(d, exist_ok=True)

    t0 = time.time()
    # the tests' archive generator at natural client count, ~natural
    # candidate count
    generate_synthetic_personachat(d, num_personalities=num_clients,
                                   dialogs_per_personality=1,
                                   utterances_per_dialog=3,
                                   num_candidates=20)
    gen_s = time.time() - t0

    tok = ByteTokenizer()
    tok.add_special_tokens(SPECIAL_TOKENS)

    t0 = time.time()
    ds = FedPERSONA(tok, 2, 2, 1, d, "PERSONA", train=True)
    prep_s = time.time() - t0  # includes prepare_datasets (first run)

    t0 = time.time()
    ds2 = FedPERSONA(tok, 2, 2, 1, d, "PERSONA", train=True)
    load_s = time.time() - t0  # stats-only reload

    n_items = len(ds2)
    n_access = min(5000, n_items)
    idxs = random.Random(1).sample(range(n_items), n_access)
    t0 = time.time()
    for i in idxs:
        ds2[i]
    access_s = time.time() - t0

    sampler = FedSampler(ds2, 16, 4, seed=0)
    t0 = time.time()
    rounds = 0
    for spec in sampler:
        rounds += 1
        if rounds >= 200:
            break
    sample_s = time.time() - t0

    return {
        "persona_clients": num_clients,
        "persona_items": n_items,
        "persona_archive_gen_s": round(gen_s, 2),
        "persona_prepare_s": round(prep_s, 2),
        "persona_reload_s": round(load_s, 3),
        "persona_item_access_per_s": round(n_access / access_s),
        "persona_sampler_rounds_per_s": round(200 / sample_s),
    }


def bench_emnist(root, writers, images_per_writer):
    from commefficient_tpu.data.fed_emnist import FedEMNIST

    d = os.path.join(root, "emnist")
    for sub in ("train", "test"):
        os.makedirs(os.path.join(d, sub), exist_ok=True)
    rng = random.Random(0)

    t0 = time.time()
    # LEAF-format shards: ~100 writers per json file like LEAF emits
    per_shard = 100
    for shard in range(0, writers, per_shard):
        user_data = {}
        for w in range(shard, min(shard + per_shard, writers)):
            n = images_per_writer
            user_data[f"w{w}"] = {
                "x": [[rng.random() for _ in range(784)]
                      for _ in range(n)],
                "y": [rng.randrange(62) for _ in range(n)],
            }
        blob = {"users": list(user_data), "user_data": user_data}
        with open(os.path.join(d, "train",
                               f"all_data_{shard}.json"), "w") as f:
            json.dump(blob, f)
    # small test split
    user_data = {f"t{w}": {"x": [[0.0] * 784 for _ in range(4)],
                           "y": [rng.randrange(62) for _ in range(4)]}
                 for w in range(20)}
    with open(os.path.join(d, "test", "all_data_0.json"), "w") as f:
        json.dump({"users": list(user_data),
                   "user_data": user_data}, f)
    gen_s = time.time() - t0

    t0 = time.time()
    ds = FedEMNIST(d, "EMNIST", train=True)
    prep_s = time.time() - t0

    n_items = len(ds)
    n_access = min(20000, n_items)
    idxs = random.Random(1).sample(range(n_items), n_access)
    t0 = time.time()
    for i in idxs:
        ds[i]
    access_s = time.time() - t0

    return {
        "emnist_writers": writers,
        "emnist_images": n_items,
        "emnist_leaf_gen_s": round(gen_s, 2),
        "emnist_prepare_s": round(prep_s, 2),
        "emnist_item_access_per_s": round(n_access / access_s),
    }


def bench_clientstore(matched_clients, scale_clients, budget_bytes,
                      n_rounds, dim):
    """Client-state placement A/B + the host-only scale axis.

    Matched population: identical deterministic local_topk rounds
    through the dense device placement and the host store — the store
    path's per-round overhead (host gather + H2D + D2H + write-back)
    is the delta. Scale population: host-only (the device placement
    would need the full (N, 2*dim) f32 state resident in HBM),
    local_topk AND fedavg, under the fixed arena budget.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from commefficient_tpu.config import Config
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)

    W, B = 8, 2

    def loss(params, batch, cfg):
        pred = batch["x"] @ params["w"]
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
        return l, (l * 0.0 + 1.0,)

    def run(placement, num_clients, **mode_kw):
        base = dict(mode="local_topk", error_type="local",
                    local_momentum=0.9, virtual_momentum=0.0, k=8,
                    num_workers=W, local_batch_size=B,
                    num_clients=num_clients, seed=0,
                    clientstore=placement,
                    clientstore_bytes=budget_bytes)
        base.update(mode_kw)
        cfg = Config(**base)
        model = FedModel(None, {"w": jnp.zeros((dim,), jnp.float32)},
                         loss, cfg, padded_batch_size=B)
        opt = FedOptimizer([{"lr": 0.25}], cfg, model=model)
        rng = np.random.RandomState(1)
        ids_all = [rng.choice(num_clients, W, replace=False)
                   .astype(np.int32) for _ in range(n_rounds + 1)]
        model.attach_participant_feed(
            lambda: ids_all[model.round_index + 1]
            if model.round_index + 1 < len(ids_all) else None)

        def one_round(r):
            batch = {"client_ids": ids_all[r],
                     "x": jnp.asarray(rng.randn(W, B, dim), jnp.float32),
                     "y": jnp.asarray(rng.randn(W, B), jnp.float32),
                     "mask": jnp.ones((W, B), jnp.float32)}
            model(batch)
            opt.step()

        one_round(0)  # warmup: jit compile + first H2D
        jax.block_until_ready(model.ps_weights)
        t0 = time.time()
        for r in range(1, n_rounds + 1):
            one_round(r)
        jax.block_until_ready(model.ps_weights)
        dt = (time.time() - t0) / n_rounds
        stats = (dict(model.client_store.stats)
                 if model.client_store is not None else None)
        model.finalize()
        return dt, stats

    out = {"clientstore_budget_bytes": int(budget_bytes),
           "clientstore_state_dim": dim,
           "clientstore_rounds": n_rounds,
           "clientstore_backend": jax.default_backend()}

    dev_s, _ = run("device", matched_clients)
    host_s, _ = run("host", matched_clients)
    out["clientstore_matched_clients"] = matched_clients
    out["clientstore_device_round_ms"] = round(dev_s * 1e3, 2)
    out["clientstore_host_round_ms"] = round(host_s * 1e3, 2)
    out["clientstore_host_overhead_pct"] = round(
        (host_s / dev_s - 1.0) * 100, 1)

    lt_s, lt_stats = run("host", scale_clients)
    fa_s, _ = run("host", scale_clients, mode="fedavg",
                  error_type="none", local_momentum=0.0,
                  local_batch_size=-1)
    out["clientstore_scale_clients"] = scale_clients
    out["clientstore_scale_local_topk_rounds_per_s"] = round(
        1.0 / lt_s, 2)
    out["clientstore_scale_fedavg_rounds_per_s"] = round(1.0 / fa_s, 2)
    out["clients_resident_max_local_topk"] = int(
        lt_stats["resident_rows_max"])
    out["clientstore_scale_evictions"] = int(lt_stats["evictions"])
    out["clientstore_scale_spill_rows"] = int(lt_stats["spill_rows"])
    return out


def bench_arrival(num_clients, n_rounds, seed, burst_start,
                  burst_stop, drop_frac, straggler_every,
                  straggler_delay_s, dim=64):
    """Round throughput under a REALISTIC arrival process.

    Every other bench feeds full, punctual rounds; real federated
    rounds arrive ragged — correlated dropout bursts ("rack went
    dark") and periodic straggler stalls. This drives the same small
    sketch workload through a data/chaos.py seeded schedule (the
    two-state Markov burst chain + straggler sleeps, replayable from
    one seed) and reports the throughput delta vs the calm loader
    plus the arrival statistics the schedule actually produced —
    the host-side cost of raggedness, separated from device math
    (dead slots are masked, so the compiled program is identical)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from commefficient_tpu.config import Config
    from commefficient_tpu.data.chaos import (ArrivalSchedule,
                                              ChaosConfig,
                                              ChaosInjector)
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)

    W, B = 8, 2

    def loss(params, batch, cfg):
        pred = batch["x"] @ params["w"]
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
        return l, (l * 0.0 + 1.0,)

    def make_loader(rng):
        for r in range(n_rounds + 1):
            ids = rng.choice(num_clients, W,
                             replace=False).astype(np.int32)
            yield {"client_ids": ids,
                   "x": rng.randn(W, B, dim).astype(np.float32),
                   "y": rng.randn(W, B).astype(np.float32),
                   "mask": np.ones((W, B), np.float32)}

    def run(chaos):
        cfg = Config(mode="sketch", error_type="virtual",
                     local_momentum=0.0, virtual_momentum=0.9, k=8,
                     num_rows=3, num_cols=64, num_workers=W,
                     local_batch_size=B, num_clients=num_clients,
                     seed=seed)
        model = FedModel(None, {"w": jnp.zeros((dim,), jnp.float32)},
                         loss, cfg, padded_batch_size=B)
        opt = FedOptimizer([{"lr": 0.25}], cfg, model=model)
        loader = make_loader(np.random.RandomState(seed))
        if chaos is not None:
            loader = chaos.wrap_loader(loader)
        alive = []
        first = next(iter(loader))
        model(first)  # warmup round: jit compile
        opt.step()
        jax.block_until_ready(model.ps_weights)
        t0 = time.time()
        for batch in loader:
            alive.append(float(batch["mask"].any(axis=1).mean()))
            model(batch)
            opt.step()
        jax.block_until_ready(model.ps_weights)
        dt = (time.time() - t0) / max(len(alive), 1)
        model.finalize()
        return dt, alive

    calm_s, _ = run(None)
    chaos_cfg = ChaosConfig(seed=seed,
                            burst_start_prob=burst_start,
                            burst_stop_prob=burst_stop,
                            burst_drop_frac=drop_frac,
                            straggler_every=straggler_every,
                            straggler_delay_s=straggler_delay_s)
    chaos_s, alive = run(ChaosInjector(chaos_cfg, num_clients))

    # arrival statistics of the replayed schedule — the shared
    # data/chaos.py summary (golden-trace-pinned), not a bench-local
    # reimplementation
    stats = ArrivalSchedule.replay_stats(alive, W)
    return {
        "arrival_rounds": len(alive),
        "arrival_seed": seed,
        "arrival_calm_round_ms": round(calm_s * 1e3, 2),
        "arrival_chaos_round_ms": round(chaos_s * 1e3, 2),
        "arrival_overhead_pct": round(
            (chaos_s / calm_s - 1.0) * 100, 1),
        "arrival_burst_count": stats["burst_count"],
        "arrival_burst_rounds": stats["burst_rounds"],
        "arrival_longest_burst": stats["longest_burst"],
        "arrival_alive_frac_min": stats["alive_frac_min"],
        "arrival_alive_frac_mean": stats["alive_frac_mean"],
        "arrival_dropped_client_rounds":
            stats["dropped_client_rounds"],
    }


def bench_async(num_clients, n_rounds, k, alpha, seed, wait_unit_s,
                budget_bytes, max_delay, churn_frac, dim=64,
                ledger=""):
    """Buffered-async serving vs the synchronous barrier at the
    host-resident scale axis.

    Both legs replay the SAME churny ``ArrivalSchedule`` (one seed)
    over local_topk rounds through the host client store at
    ``num_clients`` (>= 1M by default) simulated clients. The
    synchronous leg completes a round only when its slowest client
    lands — the schedule's per-cohort max delay is paid as a real
    barrier wait of ``wait_unit_s`` per fold-step unit. The buffered
    leg (``--async_buffer_size k``) folds as soon as ``k`` arrivals
    are buffered; stale arrivals fold late with
    ``1/(1+staleness)^alpha`` weights instead of stalling the server,
    so in the primed steady state it pays dispatch only.

    ``host_gap_share`` is computed identically for both legs:
    (round-loop wall - device-dispatch span) / wall — the fraction of
    serving wall-clock the host spends NOT driving the device. The
    delta is the headline: the barrier's straggler stalls are host
    gap; the buffer absorbs them. Only the buffered leg writes the
    telemetry ledger (``--ledger``), so its meta/round records are
    the ones the ``a<K>``-keyed perf gate sees."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from commefficient_tpu.config import Config
    from commefficient_tpu.data.chaos import ArrivalSchedule
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)

    W, B = 8, 2
    assert 0 < k <= W, "async_k must be in (0, num_workers]"

    def loss(params, batch, cfg):
        pred = batch["x"] @ params["w"]
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
        return l, (l * 0.0 + 1.0,)

    def make_schedule():
        return ArrivalSchedule("churny", seed=seed,
                               max_delay=max_delay,
                               churn_frac=churn_frac)

    def run(async_k):
        cfg = Config(mode="local_topk", error_type="local",
                     local_momentum=0.9, virtual_momentum=0.0, k=8,
                     num_workers=W, local_batch_size=B,
                     num_clients=num_clients, seed=seed,
                     clientstore="host",
                     clientstore_bytes=budget_bytes,
                     async_buffer_size=async_k,
                     async_staleness_weight=alpha if async_k else 0.0,
                     ledger=ledger if async_k else "")
        model = FedModel(None, {"w": jnp.zeros((dim,), jnp.float32)},
                         loss, cfg, padded_batch_size=B)
        opt = FedOptimizer([{"lr": 0.25}], cfg, model=model)
        sched = make_schedule()
        if async_k:
            model.attach_arrival_process(sched)
        rng = np.random.RandomState(seed)
        ids_all = [rng.choice(num_clients, W, replace=False)
                   .astype(np.int32) for _ in range(n_rounds + 1)]
        model.attach_participant_feed(
            lambda: ids_all[model.round_index + 1]
            if model.round_index + 1 < len(ids_all) else None)

        def make_batch(r):
            return {"client_ids": ids_all[r],
                    "x": jnp.asarray(rng.randn(W, B, dim),
                                     jnp.float32),
                    "y": jnp.asarray(rng.randn(W, B), jnp.float32),
                    "mask": jnp.ones((W, B), jnp.float32)}

        model(make_batch(0))  # warmup: jit compile + first H2D
        opt.step()
        jax.block_until_ready(model.ps_weights)
        dispatch = 0.0
        t0 = time.time()
        for r in range(1, n_rounds + 1):
            batch = make_batch(r)
            if not async_k:
                # barrier semantics: the round closes when its
                # slowest client lands — replay the same schedule as
                # a real wait (fold-step units -> wait_unit_s)
                stall = int(sched.delays(W).max())
                if stall:
                    time.sleep(stall * wait_unit_s)
            td = time.time()
            model(batch)
            opt.step()
            jax.block_until_ready(model.ps_weights)
            dispatch += time.time() - td
        wall = time.time() - t0
        astats = (dict(model._async_driver.round_stats())
                  if async_k else {})
        store_stats = (dict(model.client_store.stats)
                       if model.client_store is not None else {})
        model.finalize()
        gap = max(wall - dispatch, 0.0) / max(wall, 1e-9)
        return wall / n_rounds, gap, astats, store_stats, cfg

    sync_s, sync_gap, _, _, _ = run(0)
    buf_s, buf_gap, astats, store_stats, acfg = run(k)

    out = {
        "async_clients": int(num_clients),
        "async_rounds": int(n_rounds),
        "async_buffer_k": int(k),
        "async_staleness_alpha": float(alpha),
        "async_seed": int(seed),
        "async_wait_unit_ms": round(wait_unit_s * 1e3, 2),
        "async_sync_round_ms": round(sync_s * 1e3, 2),
        "async_buffered_round_ms": round(buf_s * 1e3, 2),
        "async_speedup_x": round(sync_s / max(buf_s, 1e-9), 2),
        "async_sync_host_gap_share": round(sync_gap, 4),
        "async_buffered_host_gap_share": round(buf_gap, 4),
        "async_host_gap_reduction": round(sync_gap - buf_gap, 4),
        "async_staleness_mean": astats.get("async_staleness_mean"),
        "async_staleness_max": astats.get("async_staleness_max"),
        "async_backlog_final": astats.get("async_backlog"),
        "async_buffer_occupancy":
            astats.get("async_buffer_occupancy"),
    }
    if store_stats:
        out["clients_resident_max_async"] = int(
            store_stats.get("resident_rows_max", 0))
    return out, acfg


def bench_service(n_jobs, clients_per_job, n_rounds, k, alpha, seed,
                  budget_bytes, max_delay, churn_frac, dim=64,
                  ledger="", runs_dir=""):
    """Multi-tenant daemon leg: ``n_jobs`` independent federated jobs
    (>= 1M simulated host-resident clients in aggregate at the
    defaults) multiplexed over ONE pod by the fedservice scheduler,
    each job replaying its own seeded churny chaos arrival trace
    through its own buffered-async driver.

    The headline is **aggregate clients served per second per pod** —
    total client contributions folded across every tenant divided by
    the daemon's steady-state wall-clock (the warmup tick that pays
    each job's jit compile is excluded). With ``--ledger`` the value
    lands as a numeric bench record on the service ledger, so
    ``scripts/perf_gate.py`` gates it under the run's ``j<J>``
    topology key (no cross-J fallback: a 3-job pod never compares
    against a 5-job one)."""
    import jax.numpy as jnp
    import numpy as np

    from commefficient_tpu.config import Config
    from commefficient_tpu.data.chaos import ArrivalSchedule
    from commefficient_tpu.fedservice import FedService, JobSpec
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)

    W, B = 8, 2

    def loss(params, batch, cfg):
        pred = batch["x"] @ params["w"]
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
        return l, (l * 0.0 + 1.0,)

    def builder(cfg, mesh):
        model = FedModel(None, {"w": jnp.zeros((dim,), jnp.float32)},
                         loss, cfg, padded_batch_size=B, mesh=mesh)
        opt = FedOptimizer([{"lr": 0.25}], cfg, model=model)
        return model, opt

    def make_batch_fn(job_seed):
        rng = np.random.RandomState(job_seed)

        def batch_fn(r):
            return {
                "client_ids": rng.choice(clients_per_job, W,
                                         replace=False)
                .astype(np.int32),
                "x": jnp.asarray(rng.randn(W, B, dim), jnp.float32),
                "y": jnp.asarray(rng.randn(W, B), jnp.float32),
                "mask": jnp.ones((W, B), jnp.float32)}

        return batch_fn

    svc_cfg = Config(num_workers=W, local_batch_size=B,
                     num_clients=int(n_jobs) * int(clients_per_job),
                     seed=seed, ledger=ledger)
    svc = FedService(svc_cfg, policy="fair")
    rounds_per_job = n_rounds + 1  # +1: the warmup (compile) tick
    for j in range(n_jobs):
        cfg = Config(mode="local_topk", error_type="local",
                     local_momentum=0.9, virtual_momentum=0.0, k=8,
                     num_workers=W, local_batch_size=B,
                     num_clients=clients_per_job, seed=seed + j,
                     clientstore="host",
                     clientstore_bytes=budget_bytes,
                     async_buffer_size=k,
                     async_staleness_weight=alpha)
        svc.admit(JobSpec(f"tenant{j}", cfg, builder,
                          make_batch_fn(seed + j),
                          rounds=rounds_per_job))
        svc.attach_arrival_process(
            f"tenant{j}",
            ArrivalSchedule("churny", seed=seed + j,
                            max_delay=max_delay,
                            churn_frac=churn_frac))
    svc.tick()  # warmup: every tenant pays its jit compile here
    t0 = time.time()
    ticks = svc.run()
    wall = time.time() - t0
    served = sum(svc.job_rounds(f"tenant{j}") - 1
                 for j in range(n_jobs)) * W
    clients_per_s = served / max(wall, 1e-9)
    svc.close()

    out = {
        "service_jobs": int(n_jobs),
        "service_clients_total": int(n_jobs) * int(clients_per_job),
        "service_rounds_per_job": int(n_rounds),
        "service_ticks": int(ticks),
        "service_wall_s": round(wall, 3),
        "service_round_ms": round(1e3 * wall / max(ticks, 1), 2),
        "service_clients_per_s": round(clients_per_s, 1),
    }
    if ledger:
        from commefficient_tpu.telemetry import (append_bench_record,
                                                 registry)
        # the service telemetry sink is closed above, so this writer
        # is the only one on the path — and the numeric value is what
        # the perf gate reads as bench:service_clients_per_s
        append_bench_record(ledger, "service_clients_per_s",
                            out["service_clients_per_s"],
                            service_jobs=int(n_jobs))
        mp = registry.write_manifest(
            runs_dir, args=svc_cfg, ledger=ledger,
            bench=dict(out),
            extra={"service_jobs": int(n_jobs),
                   "service_run": True})
        print(f"manifest: {mp}", file=sys.stderr)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--persona_clients", type=int, default=17568)
    ap.add_argument("--emnist_writers", type=int, default=3500)
    ap.add_argument("--emnist_images", type=int, default=20)
    ap.add_argument("--workdir", type=str, default=None)
    ap.add_argument("--only", type=str, default="all",
                    choices=("all", "persona", "emnist", "clientstore",
                             "arrival", "async", "service"))
    ap.add_argument("--store_matched_clients", type=int, default=4096)
    ap.add_argument("--store_scale_clients", type=int,
                    default=1_000_000)
    ap.add_argument("--store_budget_mb", type=int, default=4)
    ap.add_argument("--store_rounds", type=int, default=20)
    ap.add_argument("--store_dim", type=int, default=256)
    ap.add_argument("--arrival_clients", type=int, default=256)
    ap.add_argument("--arrival_rounds", type=int, default=40)
    ap.add_argument("--arrival_seed", type=int, default=0)
    ap.add_argument("--arrival_burst_start", type=float, default=0.2)
    ap.add_argument("--arrival_burst_stop", type=float, default=0.5)
    ap.add_argument("--arrival_drop_frac", type=float, default=0.5)
    ap.add_argument("--arrival_straggler_every", type=int, default=10)
    ap.add_argument("--arrival_straggler_delay_s", type=float,
                    default=0.05)
    ap.add_argument("--async_rounds", type=int, default=40)
    ap.add_argument("--async_k", type=int, default=4,
                    help="buffered leg's --async_buffer_size "
                    "(cohort is 8)")
    ap.add_argument("--async_alpha", type=float, default=0.5,
                    help="buffered leg's --async_staleness_weight")
    ap.add_argument("--async_wait_unit_ms", type=float, default=5.0,
                    help="real milliseconds per fold-step unit of "
                    "schedule delay the synchronous barrier waits")
    ap.add_argument("--async_max_delay", type=int, default=4)
    ap.add_argument("--async_churn_frac", type=float, default=0.5)
    ap.add_argument("--service_jobs", type=int, default=3,
                    help="tenant count for the fedservice leg")
    ap.add_argument("--service_clients_per_job", type=int,
                    default=350_000,
                    help="simulated host-store clients per tenant "
                    "(3 x 350k >= the 1M aggregate floor)")
    ap.add_argument("--service_rounds", type=int, default=12,
                    help="steady-state rounds per tenant (warmup "
                    "tick excluded from the clients/s headline)")
    ap.add_argument("--runs_dir", type=str, default="runs",
                    help="registry directory for the async bench's "
                    "run manifest (written only with --ledger)")
    ap.add_argument("--ledger", type=str, default="",
                    help="append the result as a telemetry JSONL "
                    "bench record (stdout line unchanged)")
    args = ap.parse_args()

    root = args.workdir or tempfile.mkdtemp(prefix="host_scale_")
    print(f"workdir: {root}", file=sys.stderr)
    out = {}
    try:
        if args.only in ("all", "persona"):
            out.update(bench_persona(root, args.persona_clients))
        if args.only in ("all", "emnist"):
            out.update(bench_emnist(root, args.emnist_writers,
                                    args.emnist_images))
        if args.only in ("all", "clientstore"):
            out.update(bench_clientstore(
                args.store_matched_clients, args.store_scale_clients,
                args.store_budget_mb << 20, args.store_rounds,
                args.store_dim))
        if args.only in ("all", "arrival"):
            out.update(bench_arrival(
                args.arrival_clients, args.arrival_rounds,
                args.arrival_seed, args.arrival_burst_start,
                args.arrival_burst_stop, args.arrival_drop_frac,
                args.arrival_straggler_every,
                args.arrival_straggler_delay_s))
        if args.only in ("all", "async"):
            aout, acfg = bench_async(
                args.store_scale_clients, args.async_rounds,
                args.async_k, args.async_alpha, args.arrival_seed,
                args.async_wait_unit_ms / 1e3,
                args.store_budget_mb << 20, args.async_max_delay,
                args.async_churn_frac, ledger=args.ledger)
            out.update(aout)
            if args.ledger:
                from commefficient_tpu.telemetry import registry
                mp = registry.write_manifest(
                    args.runs_dir, args=acfg, ledger=args.ledger,
                    bench={k: v for k, v in aout.items()
                           if v is not None})
                print(f"manifest: {mp}", file=sys.stderr)
        if args.only in ("all", "service"):
            out.update(bench_service(
                args.service_jobs, args.service_clients_per_job,
                args.service_rounds, args.async_k, args.async_alpha,
                args.arrival_seed, args.store_budget_mb << 20,
                args.async_max_delay, args.async_churn_frac,
                ledger=(args.ledger if args.only == "service"
                        else ""),
                runs_dir=args.runs_dir))
    finally:
        if args.workdir is None:
            shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(out))
    if args.ledger:
        from commefficient_tpu.telemetry import append_bench_record
        append_bench_record(args.ledger, "host_scale_bench", out,
                            only=args.only)


if __name__ == "__main__":
    main()
