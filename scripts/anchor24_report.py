"""Summarise anchor24 run logs into the mode-ordering table.

Reads runs/anchor24_<mode>_s<seed>.log files (written by
scripts/anchor24.py) and prints one row per mode: final / tail-mean
(last 5 epochs) / best test accuracy, final train loss, wall-clock.
Pure log parsing — reruns nothing.

Usage: python scripts/anchor24_report.py [--logdir runs] [--seed 21]
"""

import argparse
import glob
import json
import os
import re


def parse_log(path):
    rows = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) == 11 and re.match(r"^\d+$", parts[0]):
                rows.append([float(x) for x in parts])
    if not rows:
        return None
    test_acc = [r[7] for r in rows]
    tail = test_acc[-5:]
    return {
        "epochs": len(rows),
        "final_acc": test_acc[-1],
        "tail_acc": round(sum(tail) / len(tail), 4),
        "best_acc": max(test_acc),
        "final_train_loss": rows[-1][3],
        "final_train_acc": rows[-1][4],
        "wall_s": rows[-1][10],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--logdir", default="runs")
    ap.add_argument("--seed", type=int, default=21)
    args = ap.parse_args()

    out = {}
    for path in sorted(glob.glob(os.path.join(
            args.logdir, f"anchor24_*_s{args.seed}.log"))):
        mode = os.path.basename(path)[len("anchor24_"):-len(
            f"_s{args.seed}.log")]
        rec = parse_log(path)
        if rec:
            out[mode] = rec
    order = sorted(out, key=lambda m: -out[m]["tail_acc"])
    print(json.dumps({"seed": args.seed, "ordering": order,
                      "modes": out}, indent=1))


if __name__ == "__main__":
    main()
