"""Isolation A/B of the vocab-head cross-entropy: chunked
(models/gpt2.py lm_nll_sums_chunked) vs fused Pallas
(ops/flce_pallas.py lm_nll_sums_fused), fwd+bwd, at a given
(clients, examples, tokens, width, vocab) geometry.

Times the op pair alone (hidden states precomputed, vmapped over the
client axis like the federated round) so end-to-end round effects
(sketch pipeline, transformer) don't blur the comparison.

Usage: python scripts/flce_bench.py [--clients 4] [--examples 4]
           [--tokens 255] [--width 768] [--vocab 50262]
           [--tokens_per_chunk 1024] [--reps 5] [--iters 20]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--examples", type=int, default=4)
    ap.add_argument("--tokens", type=int, default=255)
    ap.add_argument("--width", type=int, default=768)
    ap.add_argument("--vocab", type=int, default=50262)
    ap.add_argument("--tokens_per_chunk", type=int, default=1024)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--iters", type=int, default=20,
                    help="op pairs per timed call (amortizes "
                    "dispatch through the relay)")
    ap.add_argument("--allow_fallback", action="store_true",
                    help="bench even when the fused path cannot "
                    "engage (the 'fused' column is then the chunked "
                    "fallback — reported, not asserted)")
    ap.add_argument("--ledger", type=str, default="",
                    help="append the result as a telemetry JSONL "
                    "bench record (stdout line unchanged)")
    args = ap.parse_args()

    from commefficient_tpu.models.gpt2 import lm_nll_sums_chunked
    from commefficient_tpu.ops.flce_pallas import (fused_fallback_reason,
                                                   lm_nll_sums_fused)

    W, E, Tm, C, V = (args.clients, args.examples, args.tokens,
                      args.width, args.vocab)

    # the fused timing below is meaningless if lm_nll_sums_fused is
    # silently taking the chunked fallback (it used to: any off-TPU
    # run "measured" the chunked path against itself) — refuse unless
    # told otherwise. batch_mult = W: the bench vmaps the client axis
    # exactly like the federated round.
    reason = fused_fallback_reason(E, Tm, C, V, jnp.bfloat16,
                                   batch_mult=W)
    if reason is not None and not args.allow_fallback:
        print(json.dumps({"error": "fused path would not engage: "
                          + reason,
                          "hint": "pass --allow_fallback to bench "
                          "the fallback anyway"}), file=sys.stderr)
        sys.exit(2)
    rng = np.random.RandomState(0)
    h = jnp.asarray(rng.randn(W, E, Tm, C) * 0.02, jnp.float32)
    w = jnp.asarray(rng.randn(V, C) * 0.02, jnp.float32)
    lab = jnp.asarray(rng.randint(0, V, (W, E, Tm)), jnp.int32)

    def bench(fn, kw):
        def per_client(h, lab, w):
            sn, sv = fn(h, w, lab, jnp.bfloat16, **kw)
            return jnp.sum(sn) / jnp.maximum(jnp.sum(sv), 1.0)

        def loss(h, w):
            return jnp.sum(jax.vmap(per_client, (0, 0, None))(
                h, lab, w))

        g = jax.grad(loss, argnums=(0, 1))

        @jax.jit
        def step(h, w):
            def body(_, carry):
                dh, dw = g(carry[0], carry[1])
                # feed grads back in so iterations can't be CSE'd
                return (carry[0] + 1e-12 * dh, carry[1] + 1e-12 * dw)
            h2, w2 = jax.lax.fori_loop(0, args.iters, body, (h, w))
            return jnp.sum(h2[..., 0]) + jnp.sum(w2[:, 0])

        s = step(h, w)
        assert np.isfinite(float(s))
        ts = []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            float(step(h, w))
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2] / args.iters * 1e3

    chunk_ms = bench(lm_nll_sums_chunked,
                     {"tokens_per_chunk": args.tokens_per_chunk})
    fused_ms = bench(lm_nll_sums_fused, {"batch_mult": W})
    out = {
        "geometry": {"clients": W, "examples": E, "tokens": Tm,
                     "width": C, "vocab": V,
                     "tokens_per_chunk": args.tokens_per_chunk},
        "chunked_ms_per_pair": round(chunk_ms, 3),
        "fused_ms_per_pair": round(fused_ms, 3),
        "speedup": round(chunk_ms / fused_ms, 3),
        "fused_path_engaged": reason is None,
        "fallback_reason": reason,
        "backend": jax.default_backend(),
    }
    print(json.dumps(out))
    if args.ledger:
        from commefficient_tpu.telemetry import append_bench_record
        append_bench_record(args.ledger, "flce_bench", out,
                            backend=jax.default_backend())


if __name__ == "__main__":
    main()
