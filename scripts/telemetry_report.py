"""Render or diff run ledgers (commefficient_tpu/telemetry JSONL).

    python scripts/telemetry_report.py runs/a.jsonl
        one-run summary: round program, per-span totals/means, comm
        byte totals, counters, memory watermarks, epoch table, bench
        records

    python scripts/telemetry_report.py runs/a.jsonl runs/b.jsonl
        diff two ledgers: per-span mean deltas, comm/byte deltas,
        bench metric ratios — the "did my change help" view

    python scripts/telemetry_report.py --runs_dir runs
        registry mode: list recent manifest-registered runs
        (telemetry/registry.py), summarize the latest run's ledger,
        diff it against the previous COMPARABLE run (same config hash
        AND same (device_count, process_count) topology — an 8-device
        run never diffs against a single-chip one), and render any
        scaling curves (scripts/scaling_bench.py sweeps) found in the
        registry — no hand-typed paths

    python scripts/telemetry_report.py --audit
        findings diff: the committed audit_baseline.json vs a fresh
        two-tier lint run — waived/new/fixed counts per rule, the
        "did this branch move the static-analysis needle" view

    python scripts/telemetry_report.py runs/a.jsonl --critpath
        critical-path explainer for a --causal_trace ledger
        (schema v7 ``causal`` stamps): per-round critical-path
        bucket attribution, the aggregate bucket shares, and a
        top-K slowest-rounds explainer diffed against the typical
        (per-bucket median) round — the "why is this round slow"
        view

Schema-v3 ledgers additionally render the trace-derived device-time
breakdown (compute / collective / transfer / host-gap per round) and
the roofline expectation next to the host-span percentiles. Schema-v4
ledgers add per-device lanes (busy/collective/wait/wire per device),
round collective-skew stats, and — for merged multi-host ledgers
(scripts/ledger_merge.py) — per-process shard summaries with each
host's gap. ``--json`` prints the summary (or diff) as one JSON
object instead of text. Invalid records are reported but don't abort
the render.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from commefficient_tpu.telemetry.record import validate_record  # noqa: E402


def load_ledger(path):
    """Parse a JSONL ledger -> (records, problems). Problems carry
    the 1-based line number; bad lines are skipped, not fatal."""
    records, problems = [], []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as exc:
                problems.append(f"line {lineno}: not JSON ({exc})")
                continue
            issues = validate_record(rec)
            if issues:
                problems.append(
                    f"line {lineno}: " + "; ".join(issues))
                continue
            records.append(rec)
    return records, problems


def job_summaries(records, ledger_path=None) -> dict:
    """Per-job summaries for a fedservice daemon run:
    ``{job_index: summary}``. Job records come from ``"job": j``
    stamps in a merged ledger (scripts/ledger_merge.py), else from
    the ``<ledger>.job<j>.jsonl`` shards living next to
    ``ledger_path`` (telemetry/sinks.py job_ledger_path layout)."""
    import glob
    import re

    by_job = {}
    for r in records:
        j = r.get("job")
        if isinstance(j, int):
            by_job.setdefault(j, []).append(r)
    if not by_job and ledger_path:
        pat = re.compile(re.escape(ledger_path)
                         + r"\.job(\d+)\.jsonl$")
        for shard in glob.glob(glob.escape(ledger_path)
                               + ".job*.jsonl"):
            m = pat.match(shard)
            if m:
                recs, _ = load_ledger(shard)
                by_job[int(m.group(1))] = recs
    return {j: summarize(recs)
            for j, recs in sorted(by_job.items())}


def _pct(sorted_vals, q):
    """Nearest-rank percentile of an already-sorted list."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def summarize(records) -> dict:
    """Aggregate a ledger's records into one summary dict. Reads both
    schema v1 (no probes/alarms) and v2 ledgers."""
    rounds = [r for r in records if r["kind"] == "round"]
    span_vals, counters = {}, {}
    probe_vals = {}          # probe key -> [(round, value), ...]
    alarm_rounds = []        # [{"round": r, "alarms": [...]}, ...]
    device_vals = {}         # v3 device-time bucket -> [seconds, ...]
    lane_vals = {}           # v4: device id -> bucket -> [seconds]
    skew_vals = {}           # v4: skew stat -> [seconds, ...]
    stragglers = {}          # v4: device id -> straggler-round count
    shard_vals = {}          # merged ledgers: "p<k>" -> aggregates
    variant_first = {}       # autopilot variant key -> first round a
                             # compile was stamped under it
    frontier_pts = []        # (uplink_bytes, recovery_error, round)
    privacy_eps = []         # v5: (round, cumulative dp_epsilon)
    dp_sigma_err = {}        # v5: dp_sigma -> [recovery_error, ...]
    dp_delta = None          # v5: the accountant's delta (constant)
    uplink = downlink = 0.0
    rss_peak = hbm_peak = None
    for r in rounds:
        for name, secs in r["spans"].items():
            span_vals.setdefault(name, []).append(float(secs))
        # v3-only: trace-derived device-time buckets
        dt = r.get("device_time") or {}
        for name, val in dt.items():
            if isinstance(val, (int, float)):
                device_vals.setdefault(name, []).append(float(val))
        # v4-only: per-device lanes + collective-skew stats
        pd = dt.get("per_device")
        if isinstance(pd, dict):
            for dev, buckets in pd.items():
                slot = lane_vals.setdefault(dev, {})
                for bname, bval in (buckets or {}).items():
                    if isinstance(bval, (int, float)):
                        slot.setdefault(bname, []).append(float(bval))
        skew = dt.get("skew")
        if isinstance(skew, dict):
            for sname in ("max_enter_delta_s", "p95_enter_delta_s"):
                sval = skew.get(sname)
                if isinstance(sval, (int, float)):
                    skew_vals.setdefault(sname, []).append(float(sval))
            dev = skew.get("straggler_device")
            if dev:
                stragglers[dev] = stragglers.get(dev, 0) + 1
        # merged multi-host ledgers: per-process shard data joined
        # onto the canonical round record (scripts/ledger_merge.py)
        shards = r.get("shards")
        if isinstance(shards, dict):
            for pk, sh in sorted(shards.items()):
                if not isinstance(sh, dict):
                    continue
                entry = shard_vals.setdefault(
                    pk, {"rounds": 0, "span_total_s": 0.0,
                         "host_gap_s": [], "rss_peak": None})
                entry["rounds"] += 1
                entry["span_total_s"] += sum(
                    float(v) for v in (sh.get("spans") or {}).values()
                    if isinstance(v, (int, float)))
                hg = sh.get("host_gap_s")
                if isinstance(hg, (int, float)):
                    entry["host_gap_s"].append(float(hg))
                rss = sh.get("host_rss_peak_bytes")
                if isinstance(rss, (int, float)) and \
                        (entry["rss_peak"] is None
                         or rss > entry["rss_peak"]):
                    entry["rss_peak"] = rss
        for name, n in r["counters"].items():
            counters[name] = counters.get(name, 0) + n
            # autopilot re-jit cache: each compile is stamped with
            # its variant key — the round it first appears is the
            # round that variant entered the program (the ledger-side
            # view of the controller's knob trajectory)
            if name.startswith("vcompile_programs:"):
                key = name.split(":", 1)[1]
                variant_first.setdefault(key, r["round"])
        uplink += r.get("uplink_bytes") or 0.0
        downlink += r.get("downlink_bytes") or 0.0
        rerr = (r.get("probes") or {}).get("recovery_error")
        rup = r.get("uplink_bytes")
        if isinstance(rerr, (int, float)) and \
                isinstance(rup, (int, float)):
            frontier_pts.append((float(rup), float(rerr),
                                 r["round"]))
        # v5: the privacy accountant's per-round ε stamp, plus the
        # noise-vs-recovery-error pairing (what each σ level cost in
        # sketch recovery — the DP analogue of the bytes frontier)
        eps = r.get("dp_epsilon")
        if isinstance(eps, (int, float)):
            privacy_eps.append((r["round"], float(eps)))
            if isinstance(r.get("dp_delta"), (int, float)):
                dp_delta = float(r["dp_delta"])
        sig = r.get("dp_sigma")
        if isinstance(sig, (int, float)) and \
                isinstance(rerr, (int, float)):
            dp_sigma_err.setdefault(float(sig), []).append(float(rerr))
        # v2-only keys: absent on v1 records, hence .get
        for key, val in (r.get("probes") or {}).items():
            if isinstance(val, (int, float)):
                probe_vals.setdefault(key, []).append(
                    (r["round"], float(val)))
        if r.get("alarms"):
            alarm_rounds.append({"round": r["round"],
                                 "alarms": r["alarms"]})
        for key, best in (("host_rss_peak_bytes", rss_peak),
                          ("hbm_peak_bytes", hbm_peak)):
            v = r.get(key)
            if v is not None and (best is None or v > best):
                if key == "host_rss_peak_bytes":
                    rss_peak = v
                else:
                    hbm_peak = v
    n = max(len(rounds), 1)
    spans = {}
    for name, vals in sorted(span_vals.items()):
        sv = sorted(vals)
        spans[name] = {"total_s": round(sum(vals), 4),
                       "mean_ms": round(1e3 * sum(vals) / n, 3),
                       "p50_ms": round(1e3 * _pct(sv, 50), 3),
                       "p95_ms": round(1e3 * _pct(sv, 95), 3),
                       "max_ms": round(1e3 * sv[-1], 3)}
    probes = {}
    for key, pairs in sorted(probe_vals.items()):
        vals = [v for _, v in pairs]
        probes[key] = {"n": len(vals),
                       "first": vals[0], "last": vals[-1],
                       "mean": sum(vals) / len(vals),
                       "max": max(vals)}
    device_time = {}
    for name, vals in sorted(device_vals.items()):
        sv = sorted(vals)
        if name == "roofline_utilization":
            device_time[name] = {"n": len(sv),
                                 "mean": round(sum(sv) / len(sv), 4),
                                 "min": round(sv[0], 4),
                                 "max": round(sv[-1], 4)}
        else:
            device_time[name] = {
                "n": len(sv),
                "total_s": round(sum(sv), 4),
                "mean_ms": round(1e3 * sum(sv) / len(sv), 3),
                "p50_ms": round(1e3 * _pct(sv, 50), 3),
                "p95_ms": round(1e3 * _pct(sv, 95), 3)}
    # overlap fraction (--overlap_depth pipelining): how much of the
    # round's collective wall time ran hidden under some lane's
    # compute — 0.0 for serial rounds, the pipeline's win otherwise
    overlap_fraction = None
    if "overlapped_s" in device_vals and "collective_s" in device_vals:
        coll_total = sum(device_vals["collective_s"])
        if coll_total > 0:
            overlap_fraction = round(
                sum(device_vals["overlapped_s"]) / coll_total, 4)
    per_device = {}
    for dev, buckets in sorted(lane_vals.items()):
        per_device[dev] = {
            bname: round(1e3 * sum(vals) / len(vals), 3)
            for bname, vals in sorted(buckets.items())}
    collective_skew = None
    if skew_vals:
        collective_skew = {"stragglers": dict(sorted(
            stragglers.items()))}
        for sname, vals in sorted(skew_vals.items()):
            collective_skew[sname] = {
                "mean_ms": round(1e3 * sum(vals) / len(vals), 6),
                "max_ms": round(1e3 * max(vals), 6),
                "n": len(vals)}
    shards = {}
    for pk, entry in sorted(shard_vals.items()):
        hg = entry["host_gap_s"]
        shards[pk] = {
            "rounds": entry["rounds"],
            "span_total_s": round(entry["span_total_s"], 4),
            "host_gap_mean_ms": (round(1e3 * sum(hg) / len(hg), 3)
                                 if hg else None),
            "host_rss_peak_bytes": entry["rss_peak"]}
    # per-variant compile cost (autopilot re-jit cache): the
    # vcompile_* counter triplet keyed by variant cache key —
    # raw XLA compile events, wall seconds, and whole executables
    variant_compiles = {}
    for name, n in counters.items():
        if not name.startswith("vcompile_"):
            continue
        kind, key = name.split(":", 1)
        slot = variant_compiles.setdefault(
            key, {"events": 0, "secs": 0.0, "programs": 0,
                  "first_round": variant_first.get(key)})
        if kind == "vcompile_events":
            slot["events"] = int(n)
        elif kind == "vcompile_secs":
            slot["secs"] = round(float(n), 3)
        elif kind == "vcompile_programs":
            slot["programs"] = int(n)
    # bytes-vs-recovery-error frontier: one point per uplink level
    # the controller settled on — what each byte budget bought in
    # recovery error (cheapest in-band point is the autopilot target)
    frontier = []
    by_bytes = {}
    for up, err, ridx in frontier_pts:
        by_bytes.setdefault(up, []).append((err, ridx))
    for up in sorted(by_bytes, reverse=True):
        errs = [e for e, _ in by_bytes[up]]
        frontier.append({
            "uplink_bytes": up, "rounds": len(errs),
            "first_round": min(r for _, r in by_bytes[up]),
            "err_mean": sum(errs) / len(errs),
            "err_max": max(errs)})
    # privacy trajectory (v5 DP runs): the accountant's cumulative
    # ε stamps plus one noise-vs-recovery-error point per σ level
    privacy = None
    if privacy_eps:
        privacy_eps.sort(key=lambda p: p[0])
        privacy = {
            "rounds": len(privacy_eps),
            "eps_first": privacy_eps[0][1],
            "eps_last": privacy_eps[-1][1],
            "delta": dp_delta,
            "noise_vs_recovery": [
                {"dp_sigma": s, "rounds": len(v),
                 "recovery_err_mean": sum(v) / len(v),
                 "recovery_err_max": max(v)}
                for s, v in sorted(dp_sigma_err.items())],
        }
    # v6: run alarm totals — the close()-time summary record's
    # alarm_fired backfill is authoritative (it counts fires even on
    # rounds this reader never saw, e.g. a truncated ledger); fall
    # back to counting the flagged rounds for older ledgers
    alarm_totals = {}
    for rec in records:
        if rec.get("kind") == "summary" and \
                isinstance(rec.get("alarm_fired"), dict):
            for rule, cnt in rec["alarm_fired"].items():
                alarm_totals[str(rule)] = \
                    alarm_totals.get(str(rule), 0) + int(cnt)
    if not alarm_totals:
        for a in alarm_rounds:
            for al in a["alarms"]:
                rule = str(al.get("rule", "?"))
                alarm_totals[rule] = alarm_totals.get(rule, 0) + 1
    # v6: the last round's SLO stamp is the run's closing burn state
    slo_stamp = next((r["slo"] for r in reversed(rounds)
                      if isinstance(r.get("slo"), dict)), None)
    return {
        "meta": next((r for r in records if r["kind"] == "meta"),
                     None),
        "rounds": len(rounds),
        "uplink_bytes": uplink,
        "downlink_bytes": downlink,
        "spans": spans,
        "device_time": device_time,
        "overlap_fraction": overlap_fraction,
        "per_device": per_device,
        "collective_skew": collective_skew,
        "shards": shards,
        "cost_model": next(
            (r.get("cost_model") for r in records
             if r["kind"] == "meta" and r.get("cost_model")), None),
        "probes": probes,
        "alarm_rounds": alarm_rounds,
        "alarm_totals": dict(sorted(alarm_totals.items())),
        "slo": slo_stamp,
        "variant_compiles": dict(sorted(variant_compiles.items())),
        "frontier": frontier,
        "privacy": privacy,
        "counters": dict(sorted(counters.items())),
        "host_rss_peak_bytes": rss_peak,
        "hbm_peak_bytes": hbm_peak,
        "epochs": [r["row"] for r in records if r["kind"] == "epoch"],
        "benches": [{k: v for k, v in r.items()
                     if k not in ("schema", "kind", "ts")}
                    for r in records if r["kind"] == "bench"],
        "summary_records": [r for r in records
                            if r["kind"] == "summary"],
    }


def _mib(b):
    return f"{b / 2**20:.3f} MiB"


def render_summary(s, label="") -> str:
    lines = []
    head = f"== ledger summary{' ' + label if label else ''} =="
    lines.append(head)
    meta = s["meta"]
    if meta:
        plan = meta.get("plan") or {}
        bits = [f"mode={plan.get('mode')}",
                f"grad_size={plan.get('grad_size')}",
                f"workers={plan.get('num_workers')}"]
        if "num_clients" in meta:
            bits.append(f"clients={meta['num_clients']}")
        if plan.get("fused_grad"):
            bits.append("fused_grad")
        lines.append("  run: " + ", ".join(bits))
    lines.append(f"  rounds: {s['rounds']}")
    lines.append(f"  comm: up {_mib(s['uplink_bytes'])}, "
                 f"down {_mib(s['downlink_bytes'])}")
    for name, v in s["spans"].items():
        lines.append(f"  span {name}: total {v['total_s']} s, "
                     f"mean {v['mean_ms']} ms/round"
                     f" (p50 {v['p50_ms']}, p95 {v['p95_ms']}, "
                     f"max {v['max_ms']})")
    # device-time breakdown (schema v3, --profile runs) next to the
    # host-span percentiles above
    for name, v in s.get("device_time", {}).items():
        if name == "roofline_utilization":
            lines.append(f"  device {name}: mean {v['mean']} "
                         f"(min {v['min']}, max {v['max']}, "
                         f"{v['n']} rounds)")
        else:
            lines.append(f"  device {name}: mean {v['mean_ms']} "
                         f"ms/round (p50 {v['p50_ms']}, "
                         f"p95 {v['p95_ms']}, {v['n']} rounds)")
    if s.get("overlap_fraction") is not None:
        lines.append(
            f"  overlap: {100 * s['overlap_fraction']:.1f}% of "
            "collective time hidden under compute "
            "(serial share = collective - overlapped)")
    for dev, buckets in s.get("per_device", {}).items():
        bits = ", ".join(f"{b.replace('_s', '')} {v} ms"
                         for b, v in buckets.items())
        lines.append(f"  lane {dev}: {bits} (means/round)")
    csk = s.get("collective_skew")
    if csk:
        mx = csk.get("max_enter_delta_s") or {}
        p95 = csk.get("p95_enter_delta_s") or {}
        lines.append(
            f"  collective skew: enter-delta mean "
            f"{mx.get('mean_ms')} ms, max {mx.get('max_ms')} ms "
            f"(p95-stat mean {p95.get('mean_ms')} ms, "
            f"{mx.get('n')} rounds)")
        if csk.get("stragglers"):
            lines.append(
                f"  stragglers (rounds led): {csk['stragglers']}")
    for pk, sh in s.get("shards", {}).items():
        gap = (f", host-gap mean {sh['host_gap_mean_ms']} ms"
               if sh.get("host_gap_mean_ms") is not None else "")
        rss = (f", RSS peak {_mib(sh['host_rss_peak_bytes'])}"
               if sh.get("host_rss_peak_bytes") is not None else "")
        lines.append(f"  shard {pk}: {sh['rounds']} rounds, spans "
                     f"total {sh['span_total_s']} s{gap}{rss}")
    # fedservice daemon runs: one solo-equivalent block per tenant
    for jk, js in (s.get("jobs") or {}).items():
        alarms = sum(len(a.get("alarms") or ())
                     for a in js.get("alarm_rounds") or ())
        lines.append(
            f"  job {jk}: {js['rounds']} rounds, uplink "
            f"{_mib(js['uplink_bytes'])}, downlink "
            f"{_mib(js['downlink_bytes'])}, {alarms} alarm(s)")
    cm = s.get("cost_model")
    if cm:
        lines.append(
            f"  roofline: {cm.get('label', '')} on {cm.get('chip')}"
            f" x{cm.get('n_devices')}, "
            f"{cm.get('total_flops', 0):.4g} FLOPs, expected "
            f"{cm.get('expected_round_s', 0):.6g} s/round "
            f"(compute {cm.get('compute_floor_s', 0):.6g}, "
            f"collective {cm.get('collective_floor_s', 0):.6g})")
    for name, p in s.get("probes", {}).items():
        lines.append(f"  probe {name}: first {p['first']:.6g} -> "
                     f"last {p['last']:.6g}, mean {p['mean']:.6g}, "
                     f"max {p['max']:.6g} ({p['n']} rounds)")
    for a in s.get("alarm_rounds", []):
        names = ", ".join(al.get("rule", "?") for al in a["alarms"])
        lines.append(f"  ALARM round {a['round']}: {names}")
    if s.get("alarm_totals"):
        lines.append("  alarm totals: " + ", ".join(
            f"{rule} x{n}"
            for rule, n in s["alarm_totals"].items()))
    slo = s.get("slo")
    if slo:
        for obj, st in sorted(slo.items()):
            if not isinstance(st, dict):
                continue
            lines.append(
                f"  slo {obj}: burn {st.get('burn', 0):.3g} "
                f"(target {st.get('target')}, fast rate "
                f"{st.get('fast_rate', 0):.3g}, slow rate "
                f"{st.get('slow_rate', 0):.3g}, "
                f"{st.get('seen', 0)} observed)")
    vc = s.get("variant_compiles") or {}
    if vc:
        # knob trajectory, ledger view: variants in first-dispatch
        # order (the manifest's autopilot record holds the full
        # per-round decision log for bit-exact replay)
        order = sorted(vc, key=lambda k: (
            vc[k].get("first_round")
            if vc[k].get("first_round") is not None else 1 << 30))
        lines.append("  knob trajectory: " + " -> ".join(
            f"{k}@r{vc[k]['first_round']}"
            if vc[k].get("first_round") is not None else k
            for k in order))
        for k in order:
            v = vc[k]
            lines.append(
                f"  variant {k}: {v['programs']} program(s) "
                f"compiled in {v['secs']} s "
                f"({v['events']} XLA events)")
    for p in s.get("frontier") or []:
        lines.append(
            f"  frontier {_mib(p['uplink_bytes'])}/round: "
            f"recovery err mean {p['err_mean']:.4g}, "
            f"max {p['err_max']:.4g} "
            f"({p['rounds']} round(s), from r{p['first_round']})")
    pv = s.get("privacy")
    if pv:
        delta = (f" at delta {pv['delta']:.3g}"
                 if pv.get("delta") is not None else "")
        lines.append(
            f"  privacy: eps {pv['eps_first']:.6g} -> "
            f"{pv['eps_last']:.6g}{delta} "
            f"({pv['rounds']} charged round(s))")
        for pt in pv.get("noise_vs_recovery") or []:
            lines.append(
                f"  privacy sigma {pt['dp_sigma']:.6g}: "
                f"recovery err mean {pt['recovery_err_mean']:.4g}, "
                f"max {pt['recovery_err_max']:.4g} "
                f"({pt['rounds']} round(s))")
    if s["counters"]:
        lines.append(f"  counters: {s['counters']}")
    if s["host_rss_peak_bytes"] is not None:
        lines.append(
            f"  host RSS peak: {_mib(s['host_rss_peak_bytes'])}")
    if s["hbm_peak_bytes"] is not None:
        lines.append(f"  HBM peak: {_mib(s['hbm_peak_bytes'])}")
    for row in s["epochs"]:
        lines.append("  epoch " + json.dumps(row))
    for b in s["benches"]:
        lines.append("  bench " + json.dumps(b))
    return "\n".join(lines)


def diff_summaries(a: dict, b: dict) -> dict:
    """B relative to A: per-span mean deltas, byte deltas, matching
    bench metrics as ratios (>1 = B slower/bigger)."""
    out = {"rounds": {"a": a["rounds"], "b": b["rounds"]}}
    span_diff = {}
    for name in sorted(set(a["spans"]) | set(b["spans"])):
        ma = a["spans"].get(name, {}).get("mean_ms")
        mb = b["spans"].get(name, {}).get("mean_ms")
        entry = {"a_mean_ms": ma, "b_mean_ms": mb}
        if ma and mb:
            entry["ratio"] = round(mb / ma, 3)
        span_diff[name] = entry
    out["spans"] = span_diff
    dev_diff = {}
    for name in sorted(set(a.get("device_time", {}))
                       & set(b.get("device_time", {}))):
        da, db = a["device_time"][name], b["device_time"][name]
        ka = "mean" if name == "roofline_utilization" else "mean_ms"
        entry = {"a": da[ka], "b": db[ka]}
        if da[ka]:
            entry["ratio"] = round(db[ka] / da[ka], 4)
        dev_diff[name] = entry
    if dev_diff:
        out["device_time"] = dev_diff
    fa, fb = a.get("overlap_fraction"), b.get("overlap_fraction")
    if fa is not None or fb is not None:
        out["overlap_fraction"] = {"a": fa, "b": fb}
    for key in ("uplink_bytes", "downlink_bytes"):
        entry = {"a": a[key], "b": b[key],
                 "delta": b[key] - a[key]}
        if a[key]:
            entry["ratio"] = round(b[key] / a[key], 6)
        out[key] = entry
    bench_a = {r.get("metric"): r for r in a["benches"]}
    bench_diff = {}
    for r in b["benches"]:
        ra = bench_a.get(r.get("metric"))
        if ra is None:
            continue
        va, vb = ra.get("value"), r.get("value")
        entry = {"a": va, "b": vb}
        if isinstance(va, (int, float)) and \
                isinstance(vb, (int, float)) and va:
            entry["ratio"] = round(vb / va, 4)
        bench_diff[r["metric"]] = entry
    if bench_diff:
        out["benches"] = bench_diff
    probe_diff = {}
    for name in sorted(set(a.get("probes", {}))
                       & set(b.get("probes", {}))):
        pa, pb = a["probes"][name], b["probes"][name]
        entry = {"a_mean": pa["mean"], "b_mean": pb["mean"]}
        if pa["mean"]:
            entry["ratio"] = round(pb["mean"] / pa["mean"], 4)
        probe_diff[name] = entry
    if probe_diff:
        out["probes"] = probe_diff
    vc_diff = {}
    va = a.get("variant_compiles") or {}
    vb = b.get("variant_compiles") or {}
    for key in sorted(set(va) | set(vb)):
        ea, eb = va.get(key), vb.get(key)
        vc_diff[key] = {
            "a_secs": ea["secs"] if ea else None,
            "b_secs": eb["secs"] if eb else None,
            "a_programs": ea["programs"] if ea else None,
            "b_programs": eb["programs"] if eb else None}
    if vc_diff:
        out["variant_compiles"] = vc_diff
    pa, pb = a.get("privacy"), b.get("privacy")
    if pa or pb:
        entry = {"a_eps_last": pa["eps_last"] if pa else None,
                 "b_eps_last": pb["eps_last"] if pb else None}
        if pa and pb and pa["eps_last"]:
            entry["ratio"] = round(pb["eps_last"] / pa["eps_last"], 4)
        out["privacy"] = entry
    aa = [x["round"] for x in a.get("alarm_rounds", [])]
    ab = [x["round"] for x in b.get("alarm_rounds", [])]
    if aa or ab:
        out["alarm_rounds"] = {"a": aa, "b": ab}
    return out


def render_diff(d, label_a, label_b) -> str:
    lines = [f"== ledger diff: {label_a} -> {label_b} ==",
             f"  rounds: {d['rounds']['a']} -> {d['rounds']['b']}"]
    for name, e in d["spans"].items():
        r = f" ({e['ratio']}x)" if "ratio" in e else ""
        lines.append(f"  span {name}: {e['a_mean_ms']} -> "
                     f"{e['b_mean_ms']} ms/round{r}")
    for name, e in d.get("device_time", {}).items():
        r = f" ({e['ratio']}x)" if "ratio" in e else ""
        unit = "" if name == "roofline_utilization" else " ms/round"
        lines.append(f"  device {name}: {e['a']} -> {e['b']}{unit}{r}")
    if "overlap_fraction" in d:
        e = d["overlap_fraction"]
        fmt = lambda v: f"{100 * v:.1f}%" if v is not None else "-"
        lines.append(f"  overlap fraction: {fmt(e['a'])} -> "
                     f"{fmt(e['b'])} of collective hidden")
    for key in ("uplink_bytes", "downlink_bytes"):
        e = d[key]
        r = f" ({e['ratio']}x)" if "ratio" in e else ""
        lines.append(f"  {key.split('_')[0]}: {_mib(e['a'])} -> "
                     f"{_mib(e['b'])}{r}")
    for name, e in d.get("benches", {}).items():
        r = f" ({e['ratio']}x)" if "ratio" in e else ""
        lines.append(f"  bench {name}: {e['a']} -> {e['b']}{r}")
    for name, e in d.get("probes", {}).items():
        r = f" ({e['ratio']}x)" if "ratio" in e else ""
        lines.append(f"  probe {name}: mean {e['a_mean']:.6g} -> "
                     f"{e['b_mean']:.6g}{r}")
    for key, e in d.get("variant_compiles", {}).items():
        fmt = lambda s, p: (f"{s} s / {p} prog"
                            if s is not None else "-")
        lines.append(
            f"  variant {key} compile: "
            f"{fmt(e['a_secs'], e['a_programs'])} -> "
            f"{fmt(e['b_secs'], e['b_programs'])}")
    if "privacy" in d:
        e = d["privacy"]
        fmt = lambda v: f"{v:.6g}" if v is not None else "-"
        r = f" ({e['ratio']}x)" if "ratio" in e else ""
        lines.append(f"  privacy eps spent: {fmt(e['a_eps_last'])} "
                     f"-> {fmt(e['b_eps_last'])}{r}")
    if "alarm_rounds" in d:
        e = d["alarm_rounds"]
        lines.append(f"  ALARM rounds: {e['a']} -> {e['b']}")
    return "\n".join(lines)


def scaling_curves(manifests) -> list:
    """Scaling-curve points from the registry: manifests carrying a
    ``scaling`` dict (scripts/scaling_bench.py) grouped by config
    hash, newest manifest per topology point, sorted by device count.
    Only groups with >= 2 distinct points form a curve."""
    from commefficient_tpu.telemetry import registry

    groups = {}
    for path, rec in manifests:             # oldest first
        if not isinstance(rec.get("scaling"), dict):
            continue
        by_topo = groups.setdefault(rec.get("config_hash", ""), {})
        by_topo[registry.run_topology(rec)] = (path, rec)
    curves = []
    for chash, by_topo in sorted(groups.items()):
        if len(by_topo) < 2:
            continue
        points = []
        for (dc, pc), (path, rec) in sorted(
                by_topo.items(),
                key=lambda kv: (kv[0][0] or 0, kv[0][1] or 0)):
            sc = rec["scaling"]
            points.append({
                "device_count": dc, "process_count": pc,
                "clients_per_s": sc.get("clients_per_s"),
                "parallel_efficiency": sc.get("parallel_efficiency"),
                "collective_fraction": sc.get("collective_fraction"),
                "overlapped_fraction": sc.get("overlapped_fraction"),
                "max_skew_s": sc.get("max_skew_s"),
                "manifest": path})
        curves.append({"config_hash": chash, "points": points})
    return curves


def render_scaling_curves(curves) -> str:
    lines = []
    for curve in curves:
        lines.append(f"== scaling curve (config "
                     f"{curve['config_hash'][:8] or '????????'}, "
                     f"{len(curve['points'])} points) ==")
        for p in curve["points"]:
            dc = p["device_count"]
            pc = p["process_count"]
            bits = [f"{p['clients_per_s']:.6g} clients/s"
                    if isinstance(p["clients_per_s"], (int, float))
                    else "clients/s ?"]
            if isinstance(p["parallel_efficiency"], (int, float)):
                bits.append(f"eff {p['parallel_efficiency']:.3f}")
            if isinstance(p["collective_fraction"], (int, float)):
                bits.append(
                    f"collective {100 * p['collective_fraction']:.1f}%")
            if p.get("overlapped_fraction"):
                bits.append(
                    f"overlapped "
                    f"{100 * p['overlapped_fraction']:.1f}%")
            if isinstance(p["max_skew_s"], (int, float)):
                bits.append(f"skew max {p['max_skew_s']:.6g} s")
            lines.append(f"  d{dc}p{pc}: " + ", ".join(bits))
    return "\n".join(lines)


def lineages(manifests) -> list:
    """Resume lineages from the registry: manifests carrying a
    ``resumed_from`` stamp (trainers write it from checkpoint
    metadata) grouped with the earlier same-config manifests they
    continue, oldest first. One lineage = one logical training run,
    possibly spanning several manifests and several topologies (the
    ``topology_segments`` chain records each leg)."""
    from commefficient_tpu.telemetry import registry

    by_hash = {}
    for path, rec in manifests:             # oldest first
        by_hash.setdefault(rec.get("config_hash", ""), []) \
            .append((path, rec))
    out = []
    for chash, group in sorted(by_hash.items()):
        if not any(isinstance(rec.get("resumed_from"), dict)
                   for _, rec in group):
            continue
        entries = []
        for path, rec in group:
            dc, pc = registry.run_topology(rec)
            entries.append({
                "manifest": path,
                "resumed_from": rec.get("resumed_from")
                if isinstance(rec.get("resumed_from"), dict) else None,
                "device_count": dc, "process_count": pc,
                "mesh_shape": registry.run_mesh_shape(rec),
                "segments": registry.run_segments(rec),
            })
        changed = any(registry.run_topology_changed(rec)
                      for _, rec in group)
        out.append({"config_hash": chash, "entries": entries,
                    "topology_changed": changed})
    return out


def _segment_label(seg: dict) -> str:
    dc = seg.get("device_count")
    pc = seg.get("process_count")
    label = f"d{dc}p{pc}" if dc is not None else "d?p?"
    ms = seg.get("mesh_shape")
    if isinstance(ms, dict) and ms:
        label += " " + "x".join(str(v) for v in ms.values())
    r = seg.get("round_index")
    if r is not None:
        label += f"@r{r}"
    return label


def render_lineages(lins) -> str:
    lines = []
    for lin in lins:
        lines.append(f"== resume lineage (config "
                     f"{lin['config_hash'][:8] or '????????'}, "
                     f"{len(lin['entries'])} runs) ==")
        for e in lin["entries"]:
            name = os.path.basename(e["manifest"])
            rf = e["resumed_from"]
            tail = ""
            if rf:
                src = os.path.basename(str(rf.get("checkpoint", "")))
                tail = (f" <- resumed from {src} "
                        f"(round {rf.get('round_index', '?')})")
            segs = e["segments"]
            chain = " -> ".join(_segment_label(s) for s in segs) \
                if segs else _segment_label(e)
            lines.append(f"  {name}: {chain}{tail}")
        if lin["topology_changed"]:
            lines.append("  NOTE: topology changed mid-lineage — the "
                         "perf gate treats each segment separately "
                         "and refuses to pin the merged ledger")
    return "\n".join(lines)


def runs_dir_report(runs_dir: str, as_json: bool) -> int:
    """Registry mode: list the recent manifest-registered runs, render
    the latest run's ledger, diff it against the previous COMPARABLE
    one (same config hash + topology; registry.run_key), and render
    any scaling curves the registry holds."""
    from commefficient_tpu.telemetry import registry

    manifests = registry.list_manifests(runs_dir)
    if not manifests:
        print(f"no run manifests under {runs_dir} "
              f"(runs write them when --ledger is set)")
        return 1
    if not as_json:
        print(f"== runs under {runs_dir} ({len(manifests)}) ==")
        for path, rec in manifests[-10:]:
            bench = rec.get("bench") or {}
            headline = next(
                (f"{m}: {v.get('value')} {v.get('unit', '')}"
                 for m, v in bench.items()
                 if isinstance(v, dict)), "")
            dc, pc = registry.run_topology(rec)
            topo = (f"d{dc}p{pc}" if dc is not None and pc is not None
                    else "d?p?")
            print(f"  {os.path.basename(path)}: "
                  f"git {rec.get('git_sha', '')[:8]}, "
                  f"config {rec.get('config_hash', '')[:8]}, "
                  f"backend {rec.get('backend', '?')}, {topo}"
                  + (f", {headline}" if headline else ""))
    curves = scaling_curves(manifests)
    lins = lineages(manifests)
    if lins and not as_json:
        print(render_lineages(lins))
    hits = registry.latest_ledgers(runs_dir, n=1)
    if not hits:
        print("no manifest points at an existing ledger file")
        return 1
    _, latest_manifest, latest = hits[0]
    records, problems = load_ledger(latest)
    for p in problems:
        print(f"WARNING {latest}: {p}", file=sys.stderr)
    summ = summarize(records)
    # previous COMPARABLE run only: same config hash AND topology —
    # pairing the newest two manifests regardless of device count
    # made an 8-device run "regress" against a single-chip baseline
    key = registry.run_key(latest_manifest)
    prev_hits = registry.latest_ledgers(runs_dir, n=2, key=key)
    prev = prev_hits[1][2] if len(prev_hits) > 1 else None
    if prev is None:
        if as_json:
            print(json.dumps({"latest": summ,
                              "scaling_curves": curves,
                              "lineages": lins}))
        else:
            print(render_summary(summ, label=latest))
            if not len(prev_hits) > 1:
                print("(no previous run with this config+topology "
                      "to diff against)")
            if curves:
                print(render_scaling_curves(curves))
        return 0
    records_p, problems_p = load_ledger(prev)
    for p in problems_p:
        print(f"WARNING {prev}: {p}", file=sys.stderr)
    d = diff_summaries(summarize(records_p), summ)
    if as_json:
        print(json.dumps({"latest": summ, "diff_vs_previous": d,
                          "scaling_curves": curves,
                          "lineages": lins}))
    else:
        print(render_summary(summ, label=latest))
        print(render_diff(d, prev, latest))
        if curves:
            print(render_scaling_curves(curves))
    return 0


def critpath_report(records, as_json: bool, top_k: int = 5) -> int:
    """Critical-path explainer over a --causal_trace ledger: fold
    each round's causal span DAG into per-bucket seconds
    (telemetry/critpath.py), then render per-round attributions, the
    aggregate bucket shares, and the top-K slowest rounds each
    diffed against the per-bucket median round."""
    from commefficient_tpu.telemetry.causal import BUCKETS
    from commefficient_tpu.telemetry.critpath import (critical_path,
                                                      critpath_diff,
                                                      dominant_bucket,
                                                      median_buckets)
    crits = []
    for r in records:
        if r.get("kind") != "round" \
                or not isinstance(r.get("causal"), dict):
            continue
        c = critical_path(r["causal"], r.get("device_time"))
        if c is not None:
            if isinstance(r.get("job"), int):
                c["job"] = r["job"]
            crits.append(c)
    if not crits:
        print("no causal data in this ledger — pre-v7 records, or "
              "the run did not set --causal_trace")
        return 1
    base = median_buckets(crits)
    wall_total = sum(c["wall"] for c in crits)
    shares = {b: sum(c["buckets"][b] for c in crits) for b in BUCKETS}
    slowest = sorted(crits, key=lambda c: c["wall"],
                     reverse=True)[:top_k]
    if as_json:
        print(json.dumps({
            "rounds": crits, "median_buckets": base,
            "aggregate": {"wall_s": wall_total, "buckets": shares},
            "slowest": [{"crit": c,
                         "diff_vs_median": critpath_diff(c, base)}
                        for c in slowest]}))
        return 0
    lines = [f"== critical path ({len(crits)} traced round(s)) =="]
    for c in crits:
        dom = dominant_bucket(c)
        job = f" job {c['job']}" if "job" in c else ""
        top = ", ".join(
            f"{b} {1e3 * s:.3f} ms"
            for b, s in sorted(c["buckets"].items(),
                               key=lambda kv: kv[1],
                               reverse=True)[:3] if s > 0)
        head = (f"{dom[0]} {100 * dom[1]:.0f}%"
                if dom else "idle")
        lines.append(f"  round {c['round']}{job}: wall "
                     f"{1e3 * c['wall']:.3f} ms, {head} ({top})")
    lines.append("  aggregate bucket shares:")
    for b in BUCKETS:
        s = shares[b]
        if s <= 0:
            continue
        pct = 100 * s / wall_total if wall_total else 0.0
        lines.append(f"    {b:18} {s:10.4f} s  {pct:5.1f}%")
    lines.append(f"  slowest {len(slowest)} round(s) vs the "
                 "median round:")
    for c in slowest:
        d = critpath_diff(c, base)
        grew = [r for r in d["rows"] if r["delta_s"] > 0][:2]
        why = "; ".join(
            f"{r['bucket']} +{1e3 * r['delta_s']:.3f} ms"
            + (f" ({r['ratio']:.1f}x)" if r["ratio"] else "")
            for r in grew) or "no bucket above the median"
        job = f" job {c['job']}" if "job" in c else ""
        lines.append(f"    round {c['round']}{job}: wall "
                     f"{1e3 * c['wall']:.3f} ms vs median "
                     f"{1e3 * d['base_wall']:.3f} ms — {why}")
    print("\n".join(lines))
    return 0


def render_critpath_diff(diff) -> str:
    """Text block for a bundle's attached critical-path diff (the
    flight recorder computes it at alarm-dump time)."""
    lines = [f"  critical-path diff: round {diff.get('round')} wall "
             f"{1e3 * diff['wall']:.3f} ms vs rolling-median "
             f"{1e3 * diff['base_wall']:.3f} ms"]
    for row in diff.get("rows") or []:
        if not row.get("cur_s") and not row.get("median_s"):
            continue
        ratio = (f", {row['ratio']:.2f}x"
                 if row.get("ratio") else "")
        lines.append(
            f"    {row['bucket']:18} {1e3 * row['cur_s']:9.3f} ms "
            f"vs {1e3 * row['median_s']:9.3f} ms median "
            f"(delta {1e3 * row['delta_s']:+9.3f} ms{ratio})")
    return "\n".join(lines)


def postmortem_report(path: str, as_json: bool) -> int:
    """Render a flight-recorder bundle: the incident header (reason,
    rule, labels, lineage), the recent compile/alarm event queue, and
    the ring's rounds summarized exactly like a ledger."""
    from commefficient_tpu.telemetry.flightrec import load_postmortem
    bundle, problems = load_postmortem(path)
    for p in problems:
        print(f"WARNING {path}: {p}", file=sys.stderr)
    rounds = [r for r in (bundle.get("rounds") or [])
              if isinstance(r, dict)]
    meta = bundle.get("meta")
    summ = summarize(([meta] if meta else []) + rounds)
    if as_json:
        print(json.dumps({"bundle": {
            k: bundle.get(k)
            for k in ("reason", "rule", "ts", "labels", "context",
                      "config_hash", "ring_rounds", "events",
                      "manifest", "environment")},
            "summary": summ, "problems": problems}))
        return 0
    lines = [f"== postmortem {path} =="]
    rule = f" rule={bundle.get('rule')}" if bundle.get("rule") else ""
    lines.append(f"  incident: {bundle.get('reason')}{rule} "
                 f"at ts {bundle.get('ts')}")
    if bundle.get("labels"):
        lines.append("  labels: " + ", ".join(
            f"{k}={v}" for k, v in sorted(bundle["labels"].items())))
    lines.append(f"  config: {bundle.get('config_hash', '')[:12]}"
                 + (f", manifest {bundle['manifest']}"
                    if bundle.get("manifest") else ""))
    ctx = dict(bundle.get("context") or {})
    critdiff = ctx.pop("critpath_diff", None)
    if ctx:
        lines.append("  context: " + json.dumps(ctx, sort_keys=True))
    if isinstance(critdiff, dict):
        lines.append(render_critpath_diff(critdiff))
    elif not any(isinstance(r.get("causal"), dict) for r in rounds):
        lines.append("  critical path: no causal data (pre-v7 "
                     "bundle, or the run did not set --causal_trace)")
    lines.append(f"  ring: {len(rounds)} of last "
                 f"{bundle.get('ring_rounds')} round(s) retained")
    for ev in bundle.get("events") or []:
        kind = ev.get("kind")
        if kind == "alarm":
            lines.append(
                f"  event alarm {ev.get('rule')} round "
                f"{ev.get('round')}: value {ev.get('value')} over "
                f"threshold {ev.get('threshold')}")
        elif kind == "compile":
            lines.append(
                f"  event compile round {ev.get('round')}: "
                f"{ev.get('events')} event(s), {ev.get('secs')} s")
    print("\n".join(lines))
    print(render_summary(summ, label="(flight-recorder ring)"))
    return 0


def _finding_rule(finding: str) -> str:
    """Rule name out of a rendered finding: ``path:NN: rule: msg``."""
    parts = finding.split(": ", 2)
    return parts[1] if len(parts) >= 3 else "?"


def audit_report(baseline_path: str, as_json: bool,
                 program=None, violations=None) -> int:
    """Findings diff: the committed audit baseline vs a fresh run of
    both lint tiers (legacy rules + flowlint checkers). Per rule:
    how many waived findings stand, which are NEW since the baseline
    (including any unwaived hit — those never enter a baseline), and
    which the baseline still carries but the tree has FIXED."""
    from commefficient_tpu.analysis.baseline import load_baseline
    from commefficient_tpu.analysis.lint import (run_all,
                                                 stale_waivers)
    baseline = load_baseline(baseline_path)
    pinned = set(baseline.get("lint", {}).get("waived", []))
    if violations is None:
        violations = run_all(program=program)
    stale = stale_waivers(violations=violations)
    fresh_waived = {str(v) for v in violations if v.waived}
    fresh_unwaived = sorted(str(v) for v in violations
                            if not v.waived)
    new = sorted(fresh_waived - pinned) + fresh_unwaived
    fixed = sorted(pinned - fresh_waived)

    per_rule: dict = {}
    for bucket, findings in (("waived", sorted(fresh_waived)),
                             ("new", new), ("fixed", fixed)):
        for f in findings:
            entry = per_rule.setdefault(
                _finding_rule(f), {"waived": 0, "new": 0, "fixed": 0})
            entry[bucket] += 1
    if as_json:
        print(json.dumps({
            "baseline": baseline_path, "per_rule": per_rule,
            "new": new, "fixed": fixed,
            "waived": sorted(fresh_waived),
            "unwaived": fresh_unwaived, "stale_waivers": stale}))
        return 1 if (new or fixed or stale) else 0
    lines = [f"== audit findings vs {baseline_path} =="]
    for rule in sorted(per_rule):
        c = per_rule[rule]
        lines.append(f"  {rule:24} waived {c['waived']:3}  "
                     f"new {c['new']:3}  fixed {c['fixed']:3}")
    for f in new:
        lines.append(f"  NEW   {f}")
    for f in fixed:
        lines.append(f"  FIXED {f} — refresh the baseline")
    for s in stale:
        lines.append(f"  STALE {s}")
    if not (new or fixed or stale):
        lines.append("  in sync: tree findings match the baseline")
    print("\n".join(lines))
    return 1 if (new or fixed or stale) else 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render or diff telemetry run ledgers")
    ap.add_argument("ledger", nargs="?", default=None,
                    help="run ledger (JSONL)")
    ap.add_argument("other", nargs="?", default=None,
                    help="second ledger: diff mode (other vs first)")
    ap.add_argument("--runs_dir", default=None,
                    help="registry mode: list recent runs (via their "
                         "manifests), summarize the latest ledger and "
                         "diff it against the previous run")
    ap.add_argument("--postmortem", default=None,
                    help="render a flight-recorder postmortem bundle "
                         "(telemetry/flightrec.py JSON)")
    ap.add_argument("--audit", nargs="?", const="audit_baseline.json",
                    default=None, metavar="BASELINE",
                    help="findings diff: committed audit baseline vs "
                         "a fresh two-tier lint run (new/fixed/"
                         "waived counts per rule)")
    ap.add_argument("--critpath", action="store_true",
                    help="critical-path explainer: per-round and "
                         "aggregate bucket shares plus the top-K "
                         "slowest rounds diffed against the median "
                         "round (needs a --causal_trace ledger)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    if args.audit is not None:
        return audit_report(args.audit, args.json)
    if args.postmortem is not None:
        return postmortem_report(args.postmortem, args.json)
    if args.runs_dir is not None:
        return runs_dir_report(args.runs_dir, args.json)
    if args.ledger is None:
        ap.error("a ledger path (or --runs_dir) is required")

    records, problems = load_ledger(args.ledger)
    for p in problems:
        print(f"WARNING {args.ledger}: {p}", file=sys.stderr)
    if args.critpath:
        # job records stay in: the explainer attributes per tenant
        return critpath_report(records, args.json)
    # fedservice runs: job records summarize per-tenant, not into the
    # service's own (fairness) stream
    jobs = job_summaries(records, args.ledger)
    records = [r for r in records
               if not isinstance(r.get("job"), int)]
    summ = summarize(records)

    if args.other is None:
        if jobs:
            summ["jobs"] = {str(j): s for j, s in jobs.items()}
        if args.json:
            print(json.dumps(summ))
        else:
            print(render_summary(summ, label=args.ledger))
        return 0

    records_b, problems_b = load_ledger(args.other)
    for p in problems_b:
        print(f"WARNING {args.other}: {p}", file=sys.stderr)
    d = diff_summaries(summ, summarize(records_b))
    if args.json:
        print(json.dumps(d))
    else:
        print(render_diff(d, args.ledger, args.other))
    return 0


if __name__ == "__main__":
    sys.exit(main())
