"""Render or diff run ledgers (commefficient_tpu/telemetry JSONL).

    python scripts/telemetry_report.py runs/a.jsonl
        one-run summary: round program, per-span totals/means, comm
        byte totals, counters, memory watermarks, epoch table, bench
        records

    python scripts/telemetry_report.py runs/a.jsonl runs/b.jsonl
        diff two ledgers: per-span mean deltas, comm/byte deltas,
        bench metric ratios — the "did my change help" view

    python scripts/telemetry_report.py --runs_dir runs
        registry mode: list recent manifest-registered runs
        (telemetry/registry.py), summarize the latest run's ledger and
        diff it against the previous one — no hand-typed paths

Schema-v3 ledgers additionally render the trace-derived device-time
breakdown (compute / collective / transfer / host-gap per round) and
the roofline expectation next to the host-span percentiles.
``--json`` prints the summary (or diff) as one JSON object instead of
text. Invalid records are reported but don't abort the render.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from commefficient_tpu.telemetry.record import validate_record  # noqa: E402


def load_ledger(path):
    """Parse a JSONL ledger -> (records, problems). Problems carry
    the 1-based line number; bad lines are skipped, not fatal."""
    records, problems = [], []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as exc:
                problems.append(f"line {lineno}: not JSON ({exc})")
                continue
            issues = validate_record(rec)
            if issues:
                problems.append(
                    f"line {lineno}: " + "; ".join(issues))
                continue
            records.append(rec)
    return records, problems


def _pct(sorted_vals, q):
    """Nearest-rank percentile of an already-sorted list."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def summarize(records) -> dict:
    """Aggregate a ledger's records into one summary dict. Reads both
    schema v1 (no probes/alarms) and v2 ledgers."""
    rounds = [r for r in records if r["kind"] == "round"]
    span_vals, counters = {}, {}
    probe_vals = {}          # probe key -> [(round, value), ...]
    alarm_rounds = []        # [{"round": r, "alarms": [...]}, ...]
    device_vals = {}         # v3 device-time bucket -> [seconds, ...]
    uplink = downlink = 0.0
    rss_peak = hbm_peak = None
    for r in rounds:
        for name, secs in r["spans"].items():
            span_vals.setdefault(name, []).append(float(secs))
        # v3-only: trace-derived device-time buckets
        for name, val in (r.get("device_time") or {}).items():
            if isinstance(val, (int, float)):
                device_vals.setdefault(name, []).append(float(val))
        for name, n in r["counters"].items():
            counters[name] = counters.get(name, 0) + n
        uplink += r.get("uplink_bytes") or 0.0
        downlink += r.get("downlink_bytes") or 0.0
        # v2-only keys: absent on v1 records, hence .get
        for key, val in (r.get("probes") or {}).items():
            if isinstance(val, (int, float)):
                probe_vals.setdefault(key, []).append(
                    (r["round"], float(val)))
        if r.get("alarms"):
            alarm_rounds.append({"round": r["round"],
                                 "alarms": r["alarms"]})
        for key, best in (("host_rss_peak_bytes", rss_peak),
                          ("hbm_peak_bytes", hbm_peak)):
            v = r.get(key)
            if v is not None and (best is None or v > best):
                if key == "host_rss_peak_bytes":
                    rss_peak = v
                else:
                    hbm_peak = v
    n = max(len(rounds), 1)
    spans = {}
    for name, vals in sorted(span_vals.items()):
        sv = sorted(vals)
        spans[name] = {"total_s": round(sum(vals), 4),
                       "mean_ms": round(1e3 * sum(vals) / n, 3),
                       "p50_ms": round(1e3 * _pct(sv, 50), 3),
                       "p95_ms": round(1e3 * _pct(sv, 95), 3),
                       "max_ms": round(1e3 * sv[-1], 3)}
    probes = {}
    for key, pairs in sorted(probe_vals.items()):
        vals = [v for _, v in pairs]
        probes[key] = {"n": len(vals),
                       "first": vals[0], "last": vals[-1],
                       "mean": sum(vals) / len(vals),
                       "max": max(vals)}
    device_time = {}
    for name, vals in sorted(device_vals.items()):
        sv = sorted(vals)
        if name == "roofline_utilization":
            device_time[name] = {"n": len(sv),
                                 "mean": round(sum(sv) / len(sv), 4),
                                 "min": round(sv[0], 4),
                                 "max": round(sv[-1], 4)}
        else:
            device_time[name] = {
                "n": len(sv),
                "total_s": round(sum(sv), 4),
                "mean_ms": round(1e3 * sum(sv) / len(sv), 3),
                "p50_ms": round(1e3 * _pct(sv, 50), 3),
                "p95_ms": round(1e3 * _pct(sv, 95), 3)}
    return {
        "meta": next((r for r in records if r["kind"] == "meta"),
                     None),
        "rounds": len(rounds),
        "uplink_bytes": uplink,
        "downlink_bytes": downlink,
        "spans": spans,
        "device_time": device_time,
        "cost_model": next(
            (r.get("cost_model") for r in records
             if r["kind"] == "meta" and r.get("cost_model")), None),
        "probes": probes,
        "alarm_rounds": alarm_rounds,
        "counters": dict(sorted(counters.items())),
        "host_rss_peak_bytes": rss_peak,
        "hbm_peak_bytes": hbm_peak,
        "epochs": [r["row"] for r in records if r["kind"] == "epoch"],
        "benches": [{k: v for k, v in r.items()
                     if k not in ("schema", "kind", "ts")}
                    for r in records if r["kind"] == "bench"],
        "summary_records": [r for r in records
                            if r["kind"] == "summary"],
    }


def _mib(b):
    return f"{b / 2**20:.3f} MiB"


def render_summary(s, label="") -> str:
    lines = []
    head = f"== ledger summary{' ' + label if label else ''} =="
    lines.append(head)
    meta = s["meta"]
    if meta:
        plan = meta.get("plan") or {}
        bits = [f"mode={plan.get('mode')}",
                f"grad_size={plan.get('grad_size')}",
                f"workers={plan.get('num_workers')}"]
        if "num_clients" in meta:
            bits.append(f"clients={meta['num_clients']}")
        if plan.get("fused_grad"):
            bits.append("fused_grad")
        lines.append("  run: " + ", ".join(bits))
    lines.append(f"  rounds: {s['rounds']}")
    lines.append(f"  comm: up {_mib(s['uplink_bytes'])}, "
                 f"down {_mib(s['downlink_bytes'])}")
    for name, v in s["spans"].items():
        lines.append(f"  span {name}: total {v['total_s']} s, "
                     f"mean {v['mean_ms']} ms/round"
                     f" (p50 {v['p50_ms']}, p95 {v['p95_ms']}, "
                     f"max {v['max_ms']})")
    # device-time breakdown (schema v3, --profile runs) next to the
    # host-span percentiles above
    for name, v in s.get("device_time", {}).items():
        if name == "roofline_utilization":
            lines.append(f"  device {name}: mean {v['mean']} "
                         f"(min {v['min']}, max {v['max']}, "
                         f"{v['n']} rounds)")
        else:
            lines.append(f"  device {name}: mean {v['mean_ms']} "
                         f"ms/round (p50 {v['p50_ms']}, "
                         f"p95 {v['p95_ms']}, {v['n']} rounds)")
    cm = s.get("cost_model")
    if cm:
        lines.append(
            f"  roofline: {cm.get('label', '')} on {cm.get('chip')}"
            f" x{cm.get('n_devices')}, "
            f"{cm.get('total_flops', 0):.4g} FLOPs, expected "
            f"{cm.get('expected_round_s', 0):.6g} s/round "
            f"(compute {cm.get('compute_floor_s', 0):.6g}, "
            f"collective {cm.get('collective_floor_s', 0):.6g})")
    for name, p in s.get("probes", {}).items():
        lines.append(f"  probe {name}: first {p['first']:.6g} -> "
                     f"last {p['last']:.6g}, mean {p['mean']:.6g}, "
                     f"max {p['max']:.6g} ({p['n']} rounds)")
    for a in s.get("alarm_rounds", []):
        names = ", ".join(al.get("rule", "?") for al in a["alarms"])
        lines.append(f"  ALARM round {a['round']}: {names}")
    if s["counters"]:
        lines.append(f"  counters: {s['counters']}")
    if s["host_rss_peak_bytes"] is not None:
        lines.append(
            f"  host RSS peak: {_mib(s['host_rss_peak_bytes'])}")
    if s["hbm_peak_bytes"] is not None:
        lines.append(f"  HBM peak: {_mib(s['hbm_peak_bytes'])}")
    for row in s["epochs"]:
        lines.append("  epoch " + json.dumps(row))
    for b in s["benches"]:
        lines.append("  bench " + json.dumps(b))
    return "\n".join(lines)


def diff_summaries(a: dict, b: dict) -> dict:
    """B relative to A: per-span mean deltas, byte deltas, matching
    bench metrics as ratios (>1 = B slower/bigger)."""
    out = {"rounds": {"a": a["rounds"], "b": b["rounds"]}}
    span_diff = {}
    for name in sorted(set(a["spans"]) | set(b["spans"])):
        ma = a["spans"].get(name, {}).get("mean_ms")
        mb = b["spans"].get(name, {}).get("mean_ms")
        entry = {"a_mean_ms": ma, "b_mean_ms": mb}
        if ma and mb:
            entry["ratio"] = round(mb / ma, 3)
        span_diff[name] = entry
    out["spans"] = span_diff
    dev_diff = {}
    for name in sorted(set(a.get("device_time", {}))
                       & set(b.get("device_time", {}))):
        da, db = a["device_time"][name], b["device_time"][name]
        ka = "mean" if name == "roofline_utilization" else "mean_ms"
        entry = {"a": da[ka], "b": db[ka]}
        if da[ka]:
            entry["ratio"] = round(db[ka] / da[ka], 4)
        dev_diff[name] = entry
    if dev_diff:
        out["device_time"] = dev_diff
    for key in ("uplink_bytes", "downlink_bytes"):
        entry = {"a": a[key], "b": b[key],
                 "delta": b[key] - a[key]}
        if a[key]:
            entry["ratio"] = round(b[key] / a[key], 6)
        out[key] = entry
    bench_a = {r.get("metric"): r for r in a["benches"]}
    bench_diff = {}
    for r in b["benches"]:
        ra = bench_a.get(r.get("metric"))
        if ra is None:
            continue
        va, vb = ra.get("value"), r.get("value")
        entry = {"a": va, "b": vb}
        if isinstance(va, (int, float)) and \
                isinstance(vb, (int, float)) and va:
            entry["ratio"] = round(vb / va, 4)
        bench_diff[r["metric"]] = entry
    if bench_diff:
        out["benches"] = bench_diff
    probe_diff = {}
    for name in sorted(set(a.get("probes", {}))
                       & set(b.get("probes", {}))):
        pa, pb = a["probes"][name], b["probes"][name]
        entry = {"a_mean": pa["mean"], "b_mean": pb["mean"]}
        if pa["mean"]:
            entry["ratio"] = round(pb["mean"] / pa["mean"], 4)
        probe_diff[name] = entry
    if probe_diff:
        out["probes"] = probe_diff
    aa = [x["round"] for x in a.get("alarm_rounds", [])]
    ab = [x["round"] for x in b.get("alarm_rounds", [])]
    if aa or ab:
        out["alarm_rounds"] = {"a": aa, "b": ab}
    return out


def render_diff(d, label_a, label_b) -> str:
    lines = [f"== ledger diff: {label_a} -> {label_b} ==",
             f"  rounds: {d['rounds']['a']} -> {d['rounds']['b']}"]
    for name, e in d["spans"].items():
        r = f" ({e['ratio']}x)" if "ratio" in e else ""
        lines.append(f"  span {name}: {e['a_mean_ms']} -> "
                     f"{e['b_mean_ms']} ms/round{r}")
    for name, e in d.get("device_time", {}).items():
        r = f" ({e['ratio']}x)" if "ratio" in e else ""
        unit = "" if name == "roofline_utilization" else " ms/round"
        lines.append(f"  device {name}: {e['a']} -> {e['b']}{unit}{r}")
    for key in ("uplink_bytes", "downlink_bytes"):
        e = d[key]
        r = f" ({e['ratio']}x)" if "ratio" in e else ""
        lines.append(f"  {key.split('_')[0]}: {_mib(e['a'])} -> "
                     f"{_mib(e['b'])}{r}")
    for name, e in d.get("benches", {}).items():
        r = f" ({e['ratio']}x)" if "ratio" in e else ""
        lines.append(f"  bench {name}: {e['a']} -> {e['b']}{r}")
    for name, e in d.get("probes", {}).items():
        r = f" ({e['ratio']}x)" if "ratio" in e else ""
        lines.append(f"  probe {name}: mean {e['a_mean']:.6g} -> "
                     f"{e['b_mean']:.6g}{r}")
    if "alarm_rounds" in d:
        e = d["alarm_rounds"]
        lines.append(f"  ALARM rounds: {e['a']} -> {e['b']}")
    return "\n".join(lines)


def runs_dir_report(runs_dir: str, as_json: bool) -> int:
    """Registry mode: list the recent manifest-registered runs, render
    the latest run's ledger, and diff it against the previous one."""
    from commefficient_tpu.telemetry import registry

    manifests = registry.list_manifests(runs_dir)
    if not manifests:
        print(f"no run manifests under {runs_dir} "
              f"(runs write them when --ledger is set)")
        return 1
    if not as_json:
        print(f"== runs under {runs_dir} ({len(manifests)}) ==")
        for path, rec in manifests[-10:]:
            bench = rec.get("bench") or {}
            headline = next(
                (f"{m}: {v.get('value')} {v.get('unit', '')}"
                 for m, v in bench.items()
                 if isinstance(v, dict)), "")
            print(f"  {os.path.basename(path)}: "
                  f"git {rec.get('git_sha', '')[:8]}, "
                  f"config {rec.get('config_hash', '')[:8]}, "
                  f"backend {rec.get('backend', '?')}"
                  + (f", {headline}" if headline else ""))
    hits = registry.latest_ledgers(runs_dir, n=2)
    if not hits:
        print("no manifest points at an existing ledger file")
        return 1
    _, _, latest = hits[0]
    records, problems = load_ledger(latest)
    for p in problems:
        print(f"WARNING {latest}: {p}", file=sys.stderr)
    summ = summarize(records)
    if len(hits) < 2:
        if as_json:
            print(json.dumps(summ))
        else:
            print(render_summary(summ, label=latest))
        return 0
    _, _, prev = hits[1]
    records_p, problems_p = load_ledger(prev)
    for p in problems_p:
        print(f"WARNING {prev}: {p}", file=sys.stderr)
    d = diff_summaries(summarize(records_p), summ)
    if as_json:
        print(json.dumps({"latest": summ, "diff_vs_previous": d}))
    else:
        print(render_summary(summ, label=latest))
        print(render_diff(d, prev, latest))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render or diff telemetry run ledgers")
    ap.add_argument("ledger", nargs="?", default=None,
                    help="run ledger (JSONL)")
    ap.add_argument("other", nargs="?", default=None,
                    help="second ledger: diff mode (other vs first)")
    ap.add_argument("--runs_dir", default=None,
                    help="registry mode: list recent runs (via their "
                         "manifests), summarize the latest ledger and "
                         "diff it against the previous run")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    if args.runs_dir is not None:
        return runs_dir_report(args.runs_dir, args.json)
    if args.ledger is None:
        ap.error("a ledger path (or --runs_dir) is required")

    records, problems = load_ledger(args.ledger)
    for p in problems:
        print(f"WARNING {args.ledger}: {p}", file=sys.stderr)
    summ = summarize(records)

    if args.other is None:
        if args.json:
            print(json.dumps(summ))
        else:
            print(render_summary(summ, label=args.ledger))
        return 0

    records_b, problems_b = load_ledger(args.other)
    for p in problems_b:
        print(f"WARNING {args.other}: {p}", file=sys.stderr)
    d = diff_summaries(summ, summarize(records_b))
    if args.json:
        print(json.dumps(d))
    else:
        print(render_diff(d, args.ledger, args.other))
    return 0


if __name__ == "__main__":
    sys.exit(main())
