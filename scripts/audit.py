"""Static audit CLI: program invariants + repo lint, with a committed
baseline gate.

    python scripts/audit.py                      # run both passes
    python scripts/audit.py --baseline audit_baseline.json
    python scripts/audit.py --write-baseline     # refresh the pin
    python scripts/audit.py --lint-only          # no jax, instant
    python scripts/audit.py --no-flow            # file-local rules only
    python scripts/audit.py --json report.json   # full report dump

The lint pass runs both tiers by default: the file-local legacy rules
and the flowlint whole-program checkers (call-graph trace-purity,
PRNG-key discipline, wire-dtype crossing, lock-confinement).
``--no-flow`` skips the flow tier (escape hatch for a broken parse —
file-local rules still run).

Exit status: 0 clean, 1 on any invariant failure, unwaived lint hit,
or baseline regression. The program pass always runs on the canonical
8-device virtual CPU mesh (forced below, before jax initialises its
backends) — the audit checks program *shape*, which is
platform-independent, and fingerprints are only stable on one
canonical topology.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# must precede any jax import (tests/conftest.py does the same dance)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--baseline", default=None,
                    help="diff the report against this JSON baseline")
    ap.add_argument("--write-baseline", metavar="PATH", nargs="?",
                    const="audit_baseline.json", default=None,
                    help="write the pinned baseline (default "
                         "audit_baseline.json) and exit 0 if the "
                         "audit itself is clean")
    ap.add_argument("--json", default=None,
                    help="dump the full report to this path")
    ap.add_argument("--lint-only", action="store_true")
    ap.add_argument("--program-only", action="store_true")
    ap.add_argument("--flow", dest="flow", action="store_true",
                    default=True,
                    help="run the flowlint whole-program checkers "
                         "(default)")
    ap.add_argument("--no-flow", dest="flow", action="store_false",
                    help="skip the flow tier; file-local rules only")
    args = ap.parse_args(argv)

    from commefficient_tpu.analysis import lint as lint_mod
    lint_summary = {"unwaived": [], "waived": [], "stale_waivers": []}
    if not args.program_only:
        if args.flow:
            violations = lint_mod.run_all()
            stale = lint_mod.stale_waivers(violations=violations)
        else:
            violations = lint_mod.run_lint()
            stale = lint_mod.stale_waivers(
                violations=violations,
                rule_names=[r.name for r in lint_mod.LEGACY_RULES])
        lint_summary = lint_mod.lint_report(violations, stale=stale)
        for v in lint_summary["unwaived"]:
            print(f"LINT  {v}")
        for v in stale:
            print(f"STALE {v}")
        print(f"lint: {len(lint_summary['unwaived'])} unwaived, "
              f"{len(lint_summary['waived'])} waived, "
              f"{len(stale)} stale waiver(s)")

    program_report = {"programs": {}, "failures": []}
    if not args.lint_only:
        import jax
        # the container's sitecustomize may pre-register a TPU plugin
        # that outranks the env var set above
        jax.config.update("jax_platforms", "cpu")
        from commefficient_tpu.analysis.program import \
            run_program_audit
        program_report = run_program_audit()
        for name, entry in program_report["programs"].items():
            status = "FAIL" if entry["failures"] else "ok"
            cols = entry.get("collectives", {}).get("counts", {})
            print(f"{status:4}  {name:28} "
                  f"fp {entry['fingerprint'][:12]}  "
                  f"collectives {cols or '{}'}")
        for msg in program_report["failures"]:
            print(f"AUDIT {msg}")

    from commefficient_tpu.analysis import baseline as base_mod
    report = base_mod.build_report(program_report, lint_summary)

    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        print(f"report -> {args.json}")

    failures = list(report["failures"])
    if args.write_baseline:
        if failures:
            print(f"\nNOT writing baseline: {len(failures)} hard "
                  "failure(s) — fix or waive them first")
        else:
            base_mod.save_baseline(report, args.write_baseline)
            print(f"baseline -> {args.write_baseline}")
    elif args.baseline:
        problems = base_mod.diff_against_baseline(
            report, base_mod.load_baseline(args.baseline))
        # diff_against_baseline folds the hard failures in
        failures = problems
        for p in problems:
            print(f"DIFF  {p}")

    if failures:
        print(f"\naudit: {len(failures)} failure(s)")
        return 1
    print("\naudit: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
