"""Round-5 anchor chip queue (runs the 24-epoch anchor variants the
round-4 review asked for, sequentially on the one chip):

1. Dense-baseline LR sweep (VERDICT weak #2): uncompressed + fedavg at
   --lr_scale 0.1 / 0.2, seed 21 (the 0.4 point is the existing
   anchor24_{mode}_s21.log). The review's hypothesis: the shared 0.4
   peak is over-hot for the DENSE update (uncompressed test loss rose
   2.71 -> 3.75 over epochs 22-24), so "sketch >> uncompressed" may be
   an LR artifact, not a compression-quality fact.
2. rot_lanes quality runs (VERDICT task 4): sketch mode at
   --sketch_rot_lanes 1024, seeds 21 + 22, vs the existing rot_lanes=0
   logs — 24-epoch tail_acc parity decides the large-d default.
3. local_topk at a regime where it learns (VERDICT weak #3): 100
   clients x classes_per_client 3 (the proven round-3 dial), full
   participation, seeds 21 + 22.
4. Seed-22 confirmation of each dense mode's best LR (auto-picked by
   tail_acc over the {0.1, 0.2, 0.4} sweep; 0.4 already has s22 logs).

Each anchor24.py invocation is a subprocess (fresh JAX). Logs land in
runs/ with the suffix scheme anchor24_<mode>_lr01_s21.log etc.
"""

import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = sys.executable
ANCHOR = os.path.join(REPO, "scripts", "anchor24.py")


def run(args):
    cmd = [PY, ANCHOR] + args
    print("==>", " ".join(cmd), flush=True)
    subprocess.run(cmd, cwd=REPO, check=False)


def tail_acc(log, min_epochs=20):
    """Mean test_acc of the last 5 epoch rows of an anchor log.
    Truncated/aborted logs (< min_epochs rows) return NaN so the
    best-LR pick never compares early-epoch tails against completed
    24-epoch tails."""
    accs = []
    try:
        with open(log) as f:
            for line in f:
                parts = line.split()
                # epoch rows: 11 numeric columns, col 7 = test_acc
                if len(parts) == 11 and re.match(r"^\d+$", parts[0]):
                    accs.append(float(parts[7]))
    except OSError:
        return float("nan")
    if len(accs) < min_epochs:
        return float("nan")
    t = accs[-5:]
    return sum(t) / len(t)


def main():
    # 1. dense LR sweep, seed 21
    for lr, sfx in ((0.1, "_lr01"), (0.2, "_lr02")):
        run(["--modes", "uncompressed,fedavg", "--lr_scale", str(lr),
             "--suffix", sfx])

    # 2. rot_lanes quality, seeds 21 + 22
    for seed in (21, 22):
        run(["--modes", "sketch", "--seed", str(seed),
             "--suffix", "_rl1024",
             "--extra", "--sketch_rot_lanes 1024"])

    # 3. local_topk at the learnable cpc3 regime, seeds 21 + 22
    for seed in (21, 22):
        run(["--modes", "local_topk", "--seed", str(seed),
             "--num_clients", "100", "--suffix", "_c100cpc3",
             "--extra", "--client_chunk 10 --classes_per_client 3"])

    # 4. seed-22 confirmation at each dense mode's best LR
    picks = {}
    for mode in ("uncompressed", "fedavg"):
        cand = {
            0.1: tail_acc(f"{REPO}/runs/anchor24_{mode}_lr01_s21.log"),
            0.2: tail_acc(f"{REPO}/runs/anchor24_{mode}_lr02_s21.log"),
            0.4: tail_acc(f"{REPO}/runs/anchor24_{mode}_s21.log"),
        }
        finite = [(a, lr) for lr, a in cand.items() if a == a]
        if not finite:  # all sweep logs missing/aborted: skip pick
            print(f"no usable sweep logs for {mode}; skipping "
                  f"confirmation run", flush=True)
            picks[mode] = {"sweep": cand, "best_lr": None}
            continue
        best = max(finite)[1]
        picks[mode] = {"sweep": cand, "best_lr": best}
        print(f"best lr for {mode}: {best} (sweep: {cand})", flush=True)
        if best != 0.4:  # 0.4 already has seed-22 logs
            sfx = "_lr01" if best == 0.1 else "_lr02"
            run(["--modes", mode, "--seed", "22",
                 "--lr_scale", str(best), "--suffix", sfx])

    print("R5_CHAIN_DONE " + json.dumps(picks), flush=True)


if __name__ == "__main__":
    main()
